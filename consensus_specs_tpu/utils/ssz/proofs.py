"""SSZ Merkle proofs over View objects: single proofs AND multiproofs.

Own design; fills the role of remerkleable's backing-tree proof getters that
the reference uses for light-client proofs. The verification algebra
(branch/path/helper index computation, `calculate_merkle_root`,
`calculate_multi_merkle_root`) follows the normative algorithms of
reference ssz/merkle-proofs.md:249-357; construction (`get_tree_node`,
`build_proof`, `build_multiproof`) is this engine's own: a lazy descent of
the virtual zero-padded tree that reads interior nodes straight out of the
incremental-merkleization layer caches (`_ChunkTree`) when a series has
hashed before, so proving into a 300k-validator registry costs O(log n)
node lookups instead of re-merkleizing.

``build_proof(view, *path)`` returns the branch (deepest sibling first) for
the node addressed by ``path``, suitable for
``is_valid_merkle_branch(leaf, branch, depth, get_subtree_index(gindex), root)``
with ``gindex = get_generalized_index(type(view), *path)``. Paths into
packed basic vectors/lists resolve to the CHUNK holding the element
(merkle-proofs.md:89-98 item packing); the proven leaf is that chunk.
"""
from typing import Dict, List as PyList, Sequence, Set, Tuple

from .gindex import (  # noqa: F401  (API companions)
    GeneralizedIndex,
    generalized_index_parent,
    generalized_index_sibling,
    get_generalized_index,
    get_generalized_index_bit,
    get_generalized_index_length,
)
from .ssz_typing import (
    ZERO_HASHES, Bitlist, Bitvector, ByteList, ByteVector, Container, List,
    Union, Vector, View, _ChunkTree, _type_depth, chunk_count, is_basic_type,
    merkleize_chunks, pack_bytes_into_chunks,
)
from ..hash_function import hash as sha256


# ---------------------------------------------------------------------------
# proof-shape algebra (reference ssz/merkle-proofs.md:265-302)
# ---------------------------------------------------------------------------


def get_branch_indices(tree_index: GeneralizedIndex) -> PyList[GeneralizedIndex]:
    """Sister nodes along the path from ``tree_index`` to the root,
    deepest first (merkle-proofs.md:267-277)."""
    out = [generalized_index_sibling(tree_index)]
    while out[-1] > 1:
        out.append(generalized_index_sibling(generalized_index_parent(out[-1])))
    return out[:-1]


def get_path_indices(tree_index: GeneralizedIndex) -> PyList[GeneralizedIndex]:
    """Nodes along the path itself, deepest first (merkle-proofs.md:279-289)."""
    out = [tree_index]
    while out[-1] > 1:
        out.append(generalized_index_parent(out[-1]))
    return out[:-1]


def get_helper_indices(indices: Sequence[GeneralizedIndex]) -> PyList[GeneralizedIndex]:
    """All auxiliary nodes a multiproof of ``indices`` needs, in DECREASING
    order — which reduces to the single-proof branch order for one index
    (merkle-proofs.md:291-302)."""
    helpers: Set[GeneralizedIndex] = set()
    paths: Set[GeneralizedIndex] = set()
    for index in indices:
        helpers.update(get_branch_indices(index))
        paths.update(get_path_indices(index))
    return sorted(helpers.difference(paths), reverse=True)


# ---------------------------------------------------------------------------
# verification (reference ssz/merkle-proofs.md:304-357)
# ---------------------------------------------------------------------------


def calculate_merkle_root(leaf: bytes, proof: Sequence[bytes],
                          index: GeneralizedIndex) -> bytes:
    """Root implied by a single-leaf proof (merkle-proofs.md:306-315)."""
    assert len(proof) == get_generalized_index_length(index)
    node = bytes(leaf)
    for i, h in enumerate(proof):
        if get_generalized_index_bit(index, i):
            node = sha256(bytes(h) + node)
        else:
            node = sha256(node + bytes(h))
    return node


def verify_merkle_proof(leaf: bytes, proof: Sequence[bytes],
                        index: GeneralizedIndex, root: bytes) -> bool:
    return calculate_merkle_root(leaf, proof, index) == bytes(root)


def calculate_multi_merkle_root(leaves: Sequence[bytes],
                                proof: Sequence[bytes],
                                indices: Sequence[GeneralizedIndex]) -> bytes:
    """Root implied by a multiproof: iteratively hash any node pair whose
    parent is still unknown (merkle-proofs.md:325-349)."""
    assert len(leaves) == len(indices)
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices)
    objects: Dict[int, bytes] = {}
    for index, node in zip(indices, leaves):
        objects[int(index)] = bytes(node)
    for index, node in zip(helper_indices, proof):
        objects[int(index)] = bytes(node)
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and (k ^ 1) in objects and (k // 2) not in objects:
            objects[k // 2] = sha256(objects[(k | 1) ^ 1] + objects[k | 1])
            keys.append(k // 2)
        pos += 1
    return objects[1]


def verify_merkle_multiproof(leaves: Sequence[bytes], proof: Sequence[bytes],
                             indices: Sequence[GeneralizedIndex],
                             root: bytes) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == bytes(root)


# ---------------------------------------------------------------------------
# node resolution over live views (construction side; own design)
# ---------------------------------------------------------------------------


def _chunk_layer(view) -> Tuple[PyList[bytes], PyList[View]]:
    """Bottom chunk layer of a view's own subtree + per-chunk child views
    (children only where descent below the chunk continues into an object)."""
    if isinstance(view, Container):
        names = list(view.fields())
        children = [getattr(view, n) for n in names]
        return [c.hash_tree_root() for c in children], children
    if isinstance(view, (Vector, List)) and not is_basic_type(view.ELEM_TYPE):
        children = list(view)
        return [c.hash_tree_root() for c in children], children
    if isinstance(view, (Vector, List)):  # packed basics
        data = b"".join(e.encode_bytes() for e in view)
        return list(pack_bytes_into_chunks(data)), []
    if isinstance(view, (ByteVector, ByteList)):
        return list(pack_bytes_into_chunks(bytes(view))), []
    if isinstance(view, (Bitvector, Bitlist)):
        from .ssz_typing import _bits_to_bytes

        return list(pack_bytes_into_chunks(_bits_to_bytes(list(view)))), []
    raise TypeError(f"no chunk layer for {type(view).__name__}")


def _cached_tree(view) -> "_ChunkTree | None":
    """The incremental-merkleization layer cache. `get_tree_node` hashes
    the ROOT view once up front, which recursively refreshes every
    descendant series cache that could have gone stale — so reads here
    need no per-node re-warm (a warm per node would cost an O(n) stamp
    scan each)."""
    if isinstance(view, (Vector, List, Bitlist)):
        return getattr(view, "_htr_tree", None)
    return None


def _child_at(view: View, ci: int) -> View:
    """The child OBJECT under chunk ``ci`` — without touching any other
    element (descending must not re-hash the whole series)."""
    if isinstance(view, Container):
        names = list(view.fields())
        if ci >= len(names):
            raise ValueError(f"descent below empty chunk {ci} of "
                             f"{type(view).__name__}")
        return getattr(view, names[ci])
    if isinstance(view, (Vector, List)) and not is_basic_type(view.ELEM_TYPE):
        if ci >= len(view):
            raise ValueError(f"descent below chunk {ci} of "
                             f"{type(view).__name__} (no element there)")
        return view[ci]
    raise ValueError(f"descent below chunk {ci} of {type(view).__name__} "
                     "(no child object there)")


def _tree_interior_node(tree: _ChunkTree, height: int, idx: int) -> bytes:
    """Node at (height above chunks, index) of a cached layer tree,
    honoring virtual zero padding."""
    layers = tree.layers
    if height < len(layers):
        lay = layers[height]
        return lay[idx] if idx < len(lay) else ZERO_HASHES[height]
    if idx != 0 or not layers[0]:
        return ZERO_HASHES[height]
    node = layers[-1][0]
    for lv in range(len(layers) - 1, height):
        node = sha256(node + ZERO_HASHES[lv])
    return node


def _subtree_node(chunks: PyList[bytes], height: int, idx: int) -> bytes:
    """Node at (height, idx) over an explicit zero-padded chunk list."""
    if height == 0:
        return chunks[idx] if idx < len(chunks) else b"\x00" * 32
    width = 1 << height
    seg = chunks[idx * width : (idx + 1) * width]
    return merkleize_chunks(seg, limit=width)


def _node(view: View, gindex: GeneralizedIndex) -> bytes:
    """Node lookup WITHOUT the cache-refreshing root hash — callers must
    have hashed `view` first (get_tree_node/build_* do)."""
    bits = bin(int(gindex))[3:]  # path from the root: '0' = left
    return _descend(view, bits)


def get_tree_node(view: View, gindex: GeneralizedIndex) -> bytes:
    """Value of the Merkle-tree node at ``gindex`` of ``view``'s tree.
    Descends type structure top-down; series with warm incremental caches
    answer interior nodes in O(1). The root hash up front refreshes every
    descendant cache, so the descent never re-hashes unchanged data."""
    view.hash_tree_root()
    return _node(view, gindex)


def _descend(view: View, bits: str) -> bytes:
    if not bits:
        return view.hash_tree_root()

    # mix-in layer: left = data subtree, right = mix-in leaf
    if isinstance(view, (List, ByteList, Bitlist)):
        b, rest = bits[0], bits[1:]
        if b == "1":
            if rest:
                raise ValueError("descent below a length mix-in leaf")
            return len(view).to_bytes(32, "little")
        return _descend_data(view, rest)
    if isinstance(view, Union):
        b, rest = bits[0], bits[1:]
        if b == "1":
            if rest:
                raise ValueError("descent below a selector mix-in leaf")
            return view.selector.to_bytes(32, "little")
        if view.value is None:
            if rest:
                raise ValueError("descent below a None union value")
            return b"\x00" * 32
        return _descend(view.value, rest)
    return _descend_data(view, bits)


def _descend_data(view: View, bits: str) -> bytes:
    """Descend within a view's own chunk subtree (below any mix-in)."""
    depth = _type_depth(chunk_count(type(view)))
    if len(bits) < depth:
        # interior node of this subtree
        height = depth - len(bits)
        idx = int(bits, 2) if bits else 0
        tree = _cached_tree(view)
        if tree is not None:
            return _tree_interior_node(tree, height, idx)
        chunks, _ = _chunk_layer(view)
        return _subtree_node(chunks, height, idx)
    chunk_bits, rest = bits[:depth], bits[depth:]
    ci = int(chunk_bits, 2) if chunk_bits else 0
    if not rest:
        tree = _cached_tree(view)
        if tree is not None:
            return _tree_interior_node(tree, 0, ci)
        chunks, _ = _chunk_layer(view)
        return chunks[ci] if ci < len(chunks) else b"\x00" * 32
    return _descend(_child_at(view, ci), rest)


# ---------------------------------------------------------------------------
# proof construction
# ---------------------------------------------------------------------------


def build_proof(view: View, *path) -> PyList[bytes]:
    """Single-leaf Merkle branch for the node at ``path`` (deepest sibling
    first, matching ``is_valid_merkle_branch``'s indexing). Paths ending at
    a packed basic element prove the containing CHUNK."""
    g = get_generalized_index(type(view), *path)
    view.hash_tree_root()  # one cache refresh for the whole branch
    return [_node(view, i) for i in get_branch_indices(g)]


def build_multiproof(
    view: View, gindices: Sequence[GeneralizedIndex]
) -> Tuple[PyList[bytes], PyList[bytes]]:
    """(leaves, proof) for a multiproof of ``gindices``, verifiable with
    ``verify_merkle_multiproof(leaves, proof, gindices, root)``."""
    view.hash_tree_root()  # one cache refresh for the whole proof
    leaves = [_node(view, g) for g in gindices]
    proof = [_node(view, g) for g in get_helper_indices(gindices)]
    return leaves, proof


def build_proof_bundle(
    view: View,
    *,
    paths: Sequence[Tuple] = (),
    gindices: Sequence[GeneralizedIndex] = (),
) -> Tuple[Dict[Tuple, PyList[bytes]], PyList[bytes], PyList[bytes]]:
    """Every branch (one per ``paths`` entry) AND the multiproof of
    ``gindices`` off ONE cache-refreshing root hash, with node lookups
    memoized across all of them — branches and multiproof helpers share
    most of their upper tree, so per-artifact extraction (lightclient
    proof_tree) reads each cached level node once instead of re-walking
    the descent per gindex. Returns ``(branches_by_path, leaves, proof)``.
    """
    view.hash_tree_root()  # ONE refresh for everything extracted below
    memo: Dict[int, bytes] = {}

    def node(g: GeneralizedIndex) -> bytes:
        k = int(g)
        r = memo.get(k)
        if r is None:
            r = memo[k] = _node(view, g)
        return r

    branches = {
        tuple(path): [node(i) for i in
                      get_branch_indices(
                          get_generalized_index(type(view), *path))]
        for path in paths
    }
    leaves = [node(g) for g in gindices]
    proof = [node(g) for g in get_helper_indices(gindices)]
    return branches, leaves, proof
