"""Generalized indices for SSZ Merkle trees.

Capability parity with reference ssz/merkle-proofs.md:58-248 (the reference
implements this via remerkleable's ``Path`` type, wired in at spec-build time
— reference setup.py:466-472). Spec modules import ``get_generalized_index``
and the altair light client hardcodes the two indices it needs with a
build-time assertion against these values (reference setup.py:476-481).

A generalized index addresses a node in the Merkle tree of an SSZ object:
the root is 1 and the children of node ``i`` are ``2i`` and ``2i+1``
(merkle-proofs.md:58-78).
"""
from typing import Type

from .ssz_typing import (
    Bitlist, Bitvector, ByteList, ByteVector, Container, List, Vector, View,
    chunk_count, is_basic_type, next_power_of_two,
)


class GeneralizedIndex(int):
    """A generalized Merkle-tree index (merkle-proofs.md:58-67)."""


def _item_length(typ: Type[View]) -> int:
    """Byte length of one packed element (merkle-proofs.md:89-98)."""
    if is_basic_type(typ):
        return typ.type_byte_length()
    return 32


def get_elem_type(typ: Type[View], index_or_field) -> Type[View]:
    """Type of the element addressed by a field name or element index
    (merkle-proofs.md:100-110)."""
    if issubclass(typ, Container) and isinstance(index_or_field, str):
        return typ.fields()[index_or_field]
    if issubclass(typ, (List, Vector)):
        return typ.ELEM_TYPE
    if issubclass(typ, (ByteList, ByteVector)):
        from .ssz_typing import uint8

        return uint8
    if issubclass(typ, (Bitvector, Bitlist)):
        from .ssz_typing import boolean

        return boolean
    raise TypeError(f"cannot index into {typ}")


def get_generalized_index(typ: Type[View], *path) -> GeneralizedIndex:
    """Generalized index of the node addressed by ``path`` — a sequence of
    field names (containers), element indices (vectors/lists/bitfields), or
    the sentinel ``'__len__'`` for a list's length mix-in
    (merkle-proofs.md:149-172).
    """
    root = GeneralizedIndex(1)
    for p in path:
        if p == "__len__":
            if not issubclass(typ, (List, ByteList, Bitlist)):
                raise TypeError(f"{typ} has no length mix-in")
            typ = None  # terminal
            root = GeneralizedIndex(root * 2 + 1)
            continue
        if issubclass(typ, Container) and isinstance(p, str):
            names = list(typ.fields())
            pos = names.index(p)
            base = next_power_of_two(len(names))
            root = GeneralizedIndex(root * base + pos)
            typ = typ.fields()[p]
            continue
        # series: account for the length mix-in (lists/bitlists), packing of
        # basic elements, and the bottom-layer padding to a power of two
        pos = int(p)
        elem = get_elem_type(typ, pos)
        packed_pos = pos * _item_length(elem) // 32 if not issubclass(
            typ, (Bitvector, Bitlist)
        ) else pos // 256
        base = next_power_of_two(chunk_count(typ))
        if issubclass(typ, (List, ByteList, Bitlist)):
            root = GeneralizedIndex(root * 2)  # descend into the data subtree
        root = GeneralizedIndex(root * base + packed_pos)
        typ = elem
    return root


def concat_generalized_indices(*indices: GeneralizedIndex) -> GeneralizedIndex:
    """Index of the node addressed by following each index in turn
    (merkle-proofs.md:174-186)."""
    o = GeneralizedIndex(1)
    for i in indices:
        floorpow = 1 << (int(i).bit_length() - 1)
        o = GeneralizedIndex(o * floorpow + (i - floorpow))
    return o


def get_generalized_index_length(index: GeneralizedIndex) -> int:
    """Depth of the node (merkle-proofs.md:188-196)."""
    return int(index).bit_length() - 1


def get_generalized_index_bit(index: GeneralizedIndex, position: int) -> bool:
    """Bit of the path at ``position`` (merkle-proofs.md:198-204)."""
    return (int(index) & (1 << position)) > 0


def generalized_index_sibling(index: GeneralizedIndex) -> GeneralizedIndex:
    return GeneralizedIndex(int(index) ^ 1)


def generalized_index_child(index: GeneralizedIndex, right_side: bool) -> GeneralizedIndex:
    return GeneralizedIndex(int(index) * 2 + int(right_side))


def generalized_index_parent(index: GeneralizedIndex) -> GeneralizedIndex:
    return GeneralizedIndex(int(index) // 2)
