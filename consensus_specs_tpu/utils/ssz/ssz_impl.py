"""SSZ facade: serialize / hash_tree_root / copy / uint_to_bytes.

(reference: tests/core/pyspec/eth2spec/utils/ssz/ssz_impl.py:8-25)
"""
from typing import TypeVar

from .ssz_typing import View, uint

V = TypeVar("V", bound=View)


def serialize(obj: View) -> bytes:
    return obj.encode_bytes()


def hash_tree_root(obj: View) -> "bytes":
    from time import perf_counter

    from ...merkle import levels as _levels
    from .ssz_typing import Bytes32

    t0 = perf_counter()
    root = Bytes32(obj.hash_tree_root())
    _levels.note_root_seconds(perf_counter() - t0)
    if _levels.diff_enabled():
        # CONSENSUS_SPECS_TPU_MERKLE_DIFF=1: re-derive through the pure
        # python oracle on a cold decode and demand bit-identity
        from ...merkle import plane as _plane

        _plane.diff_check(obj, root)
    return root


def uint_to_bytes(n: uint) -> bytes:
    return n.encode_bytes()


def copy(obj: V) -> V:
    return obj.copy()
