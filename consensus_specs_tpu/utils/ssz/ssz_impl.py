"""SSZ facade: serialize / hash_tree_root / copy / uint_to_bytes.

(reference: tests/core/pyspec/eth2spec/utils/ssz/ssz_impl.py:8-25)
"""
from typing import TypeVar

from .ssz_typing import View, uint

V = TypeVar("V", bound=View)


def serialize(obj: View) -> bytes:
    return obj.encode_bytes()


def hash_tree_root(obj: View) -> "bytes":
    from .ssz_typing import Bytes32

    return Bytes32(obj.hash_tree_root())


def uint_to_bytes(n: uint) -> bytes:
    return n.encode_bytes()


def copy(obj: V) -> V:
    return obj.copy()
