"""KZG polynomial commitments + DAS erasure coding over the BLS12-381
scalar field (BASELINE config #5; reference specs/das/das-core.md:63-190 and
the sharding draft's commitment machinery,
specs/sharding/beacon-chain.md:85-175, 717-721).

Own implementation in exact integer arithmetic over the curve order r
("MODULUS" in the draft specs): radix-2 (I)FFT, reverse-bit-order helpers,
the DAS extension/recovery pair, KZG commit/prove/verify for single points
and subgroup cosets (multi-proofs), and the sharding degree check. The
elliptic-curve side rides the repo's oracle (utils/bls12_381); batched
device verification reuses ops/ (the pairing plane is the same one the
signature path uses — SURVEY §2.7/P6).

``construct_proofs`` computes per-coset multiproofs by direct polynomial
division — the FK20 batch construction the draft references is an encoder
optimization, not a semantic change.
"""
from typing import List, Optional, Sequence

from . import bls12_381 as curve
from .bls12_381 import G1_GEN, G2_GEN, R as MODULUS, ec_add, ec_mul, ec_neg

PRIMITIVE_ROOT_OF_UNITY = 5  # (sharding/beacon-chain.md:104)


def root_of_unity(order: int) -> int:
    assert order & (order - 1) == 0, "order must be a power of two"
    assert (MODULUS - 1) % order == 0
    return pow(PRIMITIVE_ROOT_OF_UNITY, (MODULUS - 1) // order, MODULUS)


def is_power_of_two(n: int) -> bool:
    return n > 0 and n & (n - 1) == 0


def reverse_bit_order(n: int, order: int) -> int:
    # (das-core.md:66-73)
    assert is_power_of_two(order)
    bits = order.bit_length() - 1
    out = 0
    for _ in range(bits):
        out = (out << 1) | (n & 1)
        n >>= 1
    return out


def reverse_bit_order_list(elements: Sequence) -> List:
    # (das-core.md:75-81)
    order = len(elements)
    assert is_power_of_two(order)
    return [elements[reverse_bit_order(i, order)] for i in range(order)]


# ---------------------------------------------------------------------------
# FFT over F_r
# ---------------------------------------------------------------------------


def fft(coeffs: Sequence[int], omega: int = None) -> List[int]:
    """Evaluate the polynomial given by ``coeffs`` at the powers of omega
    (iterative radix-2, bit-reversal order internally)."""
    n = len(coeffs)
    assert is_power_of_two(n)
    if omega is None:
        omega = root_of_unity(n)
    a = [c % MODULUS for c in reverse_bit_order_list(list(coeffs))]
    length = 2
    while length <= n:
        w_len = pow(omega, n // length, MODULUS)
        for start in range(0, n, length):
            w = 1
            half = length // 2
            for i in range(start, start + half):
                u, v = a[i], a[i + half] * w % MODULUS
                a[i] = (u + v) % MODULUS
                a[i + half] = (u - v) % MODULUS
                w = w * w_len % MODULUS
        length <<= 1
    return a


def inverse_fft(evals: Sequence[int], omega: int = None) -> List[int]:
    n = len(evals)
    if omega is None:
        omega = root_of_unity(n)
    inv_n = pow(n, MODULUS - 2, MODULUS)
    out = fft(evals, pow(omega, MODULUS - 2, MODULUS))
    return [x * inv_n % MODULUS for x in out]


def das_fft_extension(data: Sequence[int]) -> List[int]:
    """Odd-index IFFT inputs making the second half of coefficients zero
    (das-core.md:89-97)."""
    poly = inverse_fft(data)
    return fft(list(poly) + [0] * len(poly))[1::2]


def extend_data(data: Sequence[int]) -> List[int]:
    # (das-core.md:113-121)
    rev_bit_odds = reverse_bit_order_list(
        das_fft_extension(reverse_bit_order_list(list(data)))
    )
    return list(data) + rev_bit_odds


def unextend_data(extended_data: Sequence[int]) -> List[int]:
    return list(extended_data[: len(extended_data) // 2])


def recover_data(subgroups: Sequence[Optional[Sequence[int]]]) -> List[int]:
    """Recover the full reverse-bit-ordered evaluation vector from >= half of
    its subgroup-aligned ranges (das-core.md:103-111).

    Exact Lagrange interpolation over the known evaluation points — O(n^2)
    but exact; the n·log^2(n) FFT-based recovery the draft links is an
    optimization of the same map."""
    sample_count = len(subgroups)
    assert is_power_of_two(sample_count)
    points_per = None
    for s in subgroups:
        if s is not None:
            points_per = len(s)
            break
    assert points_per is not None
    n = sample_count * points_per
    flat: List[Optional[int]] = [None] * n
    for si, sub in enumerate(subgroups):
        if sub is None:
            continue
        for j, y in enumerate(sub):
            flat[si * points_per + j] = y
    return recover_data_points(flat)


def recover_data_points(values: Sequence[Optional[int]]) -> List[int]:
    """Point-level recovery: ``values[i]`` is the evaluation at omega^i or
    None; any >= n/2 known points determine the (degree < n/2) polynomial.
    Raises if the known points are mutually inconsistent."""
    n = len(values)
    assert is_power_of_two(n)
    omega = root_of_unity(n)

    known = [(i, v % MODULUS) for i, v in enumerate(values) if v is not None]
    assert len(known) >= n // 2, "need at least half the points"

    xs = [pow(omega, i, MODULUS) for i, _ in known[: n // 2]]
    ys = [v for _, v in known[: n // 2]]
    coeffs = _lagrange_coeffs(xs, ys)
    assert len(coeffs) <= n // 2
    coeffs = coeffs + [0] * (n - len(coeffs))
    out = fft(coeffs, omega)
    # consistency: recovered values must agree with EVERY known point
    for i, v in known:
        assert out[i] == v, "inconsistent samples"
    return out


def _lagrange_coeffs(xs: Sequence[int], ys: Sequence[int]) -> List[int]:
    """Coefficients of the unique degree<len(xs) polynomial through points."""
    n = len(xs)
    # master product M(X) = prod (X - x_i)
    master = [1]
    for x in xs:
        master = _poly_mul(master, [(-x) % MODULUS, 1])
    coeffs = [0] * n
    for i in range(n):
        # basis_i = M / (X - x_i), scaled by 1 / basis_i(x_i)
        basis = _poly_div_linear(master, xs[i])
        denom = _poly_eval(basis, xs[i])
        scale = ys[i] * pow(denom, MODULUS - 2, MODULUS) % MODULUS
        for k in range(len(basis)):
            coeffs[k] = (coeffs[k] + basis[k] * scale) % MODULUS
    while len(coeffs) > 1 and coeffs[-1] == 0:
        coeffs.pop()
    return coeffs


def _poly_mul(a, b):
    out = [0] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                out[i + j] = (out[i + j] + ai * bj) % MODULUS
    return out


def _poly_eval(coeffs, x):
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % MODULUS
    return acc


def _poly_div_linear(coeffs, x0):
    """Quotient of coeffs / (X - x0) by synthetic division (the remainder —
    P(x0) — is dropped; callers divide where it is zero or irrelevant)."""
    n = len(coeffs)
    out = [0] * (n - 1)
    carry = coeffs[-1] % MODULUS
    for i in range(n - 2, -1, -1):
        out[i] = carry
        carry = (coeffs[i] + carry * x0) % MODULUS
    return out


# ---------------------------------------------------------------------------
# trusted setup + commitments (sharding/beacon-chain.md:168-175)
# ---------------------------------------------------------------------------


class Setup:
    """INSECURE testing setup from a known tau — the production setup comes
    from a ceremony; same shape as G1_SETUP/G2_SETUP."""

    def __init__(self, tau: int, n: int):
        self.n = n
        self.g1 = []
        self.g2 = []
        acc = 1
        for _ in range(n):
            self.g1.append(ec_mul(G1_GEN, acc))
            self.g2.append(ec_mul(G2_GEN, acc))
            acc = acc * tau % MODULUS


class _LazyPoints:
    """Indexable view of [tau^i]G computed on demand: the mainnet-shape setup
    is 16,384 points per group (MAX_SAMPLES_PER_BLOB * POINTS_PER_SAMPLE,
    sharding/beacon-chain.md:168-175) and the degree check only ever touches
    a handful of indices, so eager construction would be pure waste."""

    def __init__(self, gen, tau: int, n: int):
        self._gen = gen
        self._tau = tau
        self.n = n
        self._cache = {}

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i: int):
        if i < 0:
            i += self.n
        if not 0 <= i < self.n:
            raise IndexError(f"setup index {i} out of range (n={self.n})")
        if i not in self._cache:
            self._cache[i] = ec_mul(self._gen, pow(self._tau, i, MODULUS))
        return self._cache[i]

    def __iter__(self):
        return (self[i] for i in range(self.n))


class LazySetup:
    """Setup-compatible (``.n``/``.g1``/``.g2``) with on-demand points."""

    def __init__(self, tau: int, n: int):
        self.n = n
        self.g1 = _LazyPoints(G1_GEN, tau, n)
        self.g2 = _LazyPoints(G2_GEN, tau, n)


_lazy_setup_cache: dict = {}


def lazy_setup(tau: int, n: int) -> LazySetup:
    """Cached per (tau, n) so spec modules and test helpers share one
    point cache."""
    if (tau, n) not in _lazy_setup_cache:
        _lazy_setup_cache[(tau, n)] = LazySetup(tau, n)
    return _lazy_setup_cache[(tau, n)]


def commit_to_poly(setup: Setup, coeffs: Sequence[int]):
    """C = sum c_i * [tau^i]G1 (an MSM — the device analog is a G1 reduction
    over the batch axis, the same shape as pubkey aggregation).

    Zero coefficients are skipped before touching the setup so lazy setups
    only materialize the points a sparse polynomial (e.g. the degree-proof
    shift) actually uses."""
    assert len(coeffs) <= setup.n
    acc = None
    for i, c in enumerate(coeffs):
        if c % MODULUS:
            acc = ec_add(acc, ec_mul(setup.g1[i], c % MODULUS))
    return acc if acc is not None else ec_mul(G1_GEN, 0)


def commit_to_data(setup: Setup, data: Sequence[int]):
    """Commit to evaluation-form data (das-core.md commit_to_data)."""
    return commit_to_poly(setup, inverse_fft(reverse_bit_order_list(list(data))))


def _commit_g2(setup: Setup, coeffs: Sequence[int]):
    assert len(coeffs) <= setup.n
    acc = None
    for i, c in enumerate(coeffs):
        if c % MODULUS:
            acc = ec_add(acc, ec_mul(setup.g2[i], c % MODULUS))
    return acc if acc is not None else ec_mul(G2_GEN, 0)


def _poly_sub(a, b):
    n = max(len(a), len(b))
    return [((a[i] if i < len(a) else 0) - (b[i] if i < len(b) else 0)) % MODULUS
            for i in range(n)]


def _poly_divmod(num, den):
    num = list(num)
    out = [0] * max(1, len(num) - len(den) + 1)
    inv_lead = pow(den[-1], MODULUS - 2, MODULUS)
    for i in reversed(range(len(out))):
        if len(num) < len(den) + i:
            continue
        q = num[len(den) - 1 + i] * inv_lead % MODULUS
        out[i] = q
        for j, d in enumerate(den):
            num[i + j] = (num[i + j] - q * d) % MODULUS
    while len(num) > 1 and num[-1] == 0:
        num.pop()
    return out, num


def prove_at_point(setup: Setup, coeffs: Sequence[int], z: int):
    """KZG witness for p(z): commit((p(X) - p(z)) / (X - z))."""
    y = _poly_eval(coeffs, z)
    q, rem = _poly_divmod(_poly_sub(list(coeffs), [y]), [(-z) % MODULUS, 1])
    assert rem == [0]
    return commit_to_poly(setup, q), y


def verify_point_proof(setup: Setup, commitment, proof, z: int, y: int) -> bool:
    """e(C - [y]G1, G2) == e(pi, [tau - z]G2), as a product-of-pairings."""
    c_minus_y = ec_add(commitment, ec_neg(ec_mul(G1_GEN, y % MODULUS)))
    tau_minus_z = ec_add(setup.g2[1], ec_neg(ec_mul(G2_GEN, z % MODULUS)))
    res = curve.multi_pairing([
        (curve.ec_to_affine(c_minus_y), curve.ec_to_affine(G2_GEN)),
        (curve.ec_to_affine(ec_neg(proof)), curve.ec_to_affine(tau_minus_z)),
    ])
    return res == curve.Fq12.one()


def prove_coset(setup: Setup, coeffs: Sequence[int], x: int, coset_size: int):
    """Multi-proof for the coset {x*w^j}: commit((p - I) / Z) with
    Z = X^k - x^k and I interpolating p on the coset."""
    w = root_of_unity(coset_size)
    xs = [x * pow(w, j, MODULUS) % MODULUS for j in range(coset_size)]
    ys = [_poly_eval(coeffs, xi) for xi in xs]
    interp = _lagrange_coeffs(xs, ys)
    z_poly = [0] * (coset_size + 1)
    z_poly[0] = (-pow(x, coset_size, MODULUS)) % MODULUS
    z_poly[coset_size] = 1
    q, rem = _poly_divmod(_poly_sub(list(coeffs), interp), z_poly)
    assert all(r == 0 for r in rem), "coset evaluations inconsistent"
    return commit_to_poly(setup, q), ys


def check_multi_kzg_proof(setup: Setup, commitment, proof, x: int,
                          ys: Sequence[int]) -> bool:
    """Verify a coset multi-proof (das-core.md check_multi_kzg_proof):
    e(C - [I], G2) == e(pi, [Z(tau)]G2)."""
    coset_size = len(ys)
    w = root_of_unity(coset_size)
    xs = [x * pow(w, j, MODULUS) % MODULUS for j in range(coset_size)]
    interp = _lagrange_coeffs(xs, [y % MODULUS for y in ys])
    c_minus_i = ec_add(commitment, ec_neg(commit_to_poly(setup, interp)))
    z_poly = [0] * (coset_size + 1)
    z_poly[0] = (-pow(x, coset_size, MODULUS)) % MODULUS
    z_poly[coset_size] = 1
    z_at_tau_g2 = _commit_g2(setup, z_poly)
    res = curve.multi_pairing([
        (curve.ec_to_affine(c_minus_i), curve.ec_to_affine(G2_GEN)),
        (curve.ec_to_affine(ec_neg(proof)), curve.ec_to_affine(z_at_tau_g2)),
    ])
    return res == curve.Fq12.one()


def verify_degree_proof(setup: Setup, commitment, degree_proof,
                        points_count: int) -> bool:
    """The sharding draft's degree check
    (reference specs/sharding/beacon-chain.md:717-721):
    e(degree_proof, G2[0]) == e(commitment, G2[n - points_count]) proves
    deg(p) < points_count, with degree_proof = commit(p * X^(n - points_count))."""
    # a points_count above n would make the shift negative and (for lazy
    # setups) wrap Python-style to an unrelated point; 0 would index g2[n].
    # Reject both — don't wrap, don't IndexError
    assert 0 < points_count <= setup.n, "points_count outside 1..setup.n"
    shift = setup.n - points_count
    res = curve.multi_pairing([
        (curve.ec_to_affine(degree_proof), curve.ec_to_affine(setup.g2[0])),
        (curve.ec_to_affine(ec_neg(commitment)), curve.ec_to_affine(setup.g2[shift])),
    ])
    return res == curve.Fq12.one()


def degree_proof(setup: Setup, coeffs: Sequence[int], points_count: int):
    """commit(p(X) * X^(n - points_count)) — only exists when
    deg(p) < points_count."""
    assert len(coeffs) <= points_count
    assert 0 <= points_count <= setup.n, "points_count exceeds setup size"
    shift = setup.n - points_count
    shifted = [0] * shift + [c % MODULUS for c in coeffs]
    return commit_to_poly(setup, shifted)
