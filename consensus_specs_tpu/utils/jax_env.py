"""Process-level JAX platform forcing.

The execution environments this framework runs in (driver, CI, an operator
shell) may carry ``JAX_PLATFORMS`` pointing at an unreachable accelerator
tunnel, and a ``sitecustomize`` hook may have imported jax at interpreter
start — freezing the platform choice before any of our code runs. Setting
env vars is therefore not enough: the live jax config must be updated and
any already-initialized backends discarded.

Single home for that logic; the driver entry points (``__graft_entry__``),
the bench CLI, and the test conftest all call :func:`force_cpu`.
"""
import os
import sys
from typing import Optional


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Force the CPU platform, optionally with ``n_devices`` virtual devices.

    Safe to call whether or not jax is already imported; must be called
    before the first device op for the virtual-device count to stick
    (XLA flags are read at backend initialization).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        try:
            # NB: plain `import jax` does NOT expose jax.extend
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        except Exception:
            pass
