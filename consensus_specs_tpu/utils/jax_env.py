"""Process-level JAX platform forcing + the verify plane's mesh provider.

The execution environments this framework runs in (driver, CI, an operator
shell) may carry ``JAX_PLATFORMS`` pointing at an unreachable accelerator
tunnel, and a ``sitecustomize`` hook may have imported jax at interpreter
start — freezing the platform choice before any of our code runs. Setting
env vars is therefore not enough: the live jax config must be updated and
any already-initialized backends discarded.

Single home for that logic; the driver entry points (``__graft_entry__``),
the bench CLI, and the test conftest all call :func:`force_cpu`.

:func:`get_mesh` is the ONE place the process decides whether the verify
plane runs sharded: ``CONSENSUS_SPECS_TPU_MESH=auto|off|<n>`` resolves to a
1-D ``jax.sharding.Mesh`` over the batch axis (ROADMAP item 1 — the DP axis
of the verification batch) or ``None`` for the single-device path. The
serve plane (``serve/service.VerificationService``) acquires it at
construction and threads it through every backend call.
"""
import os
import sys
from typing import Optional

MESH_ENV = "CONSENSUS_SPECS_TPU_MESH"


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Force the CPU platform, optionally with ``n_devices`` virtual devices.

    Safe to call whether or not jax is already imported; must be called
    before the first device op for the virtual-device count to stick
    (XLA flags are read at backend initialization).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        try:
            # NB: plain `import jax` does NOT expose jax.extend
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        except Exception:
            pass


def _pow2_floor(n: int) -> int:
    b = 1
    while b * 2 <= n:
        b <<= 1
    return b


def get_mesh(spec: Optional[str] = None):
    """Resolve the process's verify-plane device mesh, or ``None``.

    ``spec`` (default: env ``CONSENSUS_SPECS_TPU_MESH``, unset == ``off``):

    - ``off``/``0``/``1``/empty — single-device path, no mesh (a 1-device
      mesh would only add dispatch overhead);
    - ``auto`` — one 1-D mesh over every visible device (largest
      power-of-two prefix), ``None`` when only one device is visible;
    - ``<n>`` — an n-device mesh. On a CPU platform with jax NOT yet
      imported, :func:`force_cpu` requests n VIRTUAL host devices first
      (``xla_force_host_platform_device_count`` is read once, at backend
      init — so the mesh-bench/smoke entry points call this before any
      device op; an already-initialized process just uses what exists,
      it never clears live backends). Counts clamp to the power-of-two
      floor of what is actually available (the batch rows pad to the
      device count, and the cross-replica butterfly reduction needs a
      power-of-two axis).

    Malformed specs resolve to ``None`` — a typo'd mesh knob must degrade
    to the proven single-device path, never crash service construction.
    The axis is named ``batch``: the only thing sharded is the
    independent-verification batch dimension.
    """
    if spec is None:
        spec = os.environ.get(MESH_ENV, "off")
    spec = spec.strip().lower()
    if spec in ("", "off", "none", "0", "1"):
        return None
    if spec == "auto":
        want = None
    else:
        try:
            want = int(spec)
        except ValueError:
            return None
        if want <= 1:
            return None

    if (want is not None and "jax" not in sys.modules
            and os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        # backend not yet initialized on plain CPU: request the virtual
        # host devices before the first jax import freezes the count.
        # NEVER after — clearing live backends mid-process would
        # invalidate every device reference already handed out.
        force_cpu(n_devices=want)
    import jax

    try:
        have = len(jax.devices())
    except Exception:
        return None
    n = _pow2_floor(have if want is None else min(want, have))
    if n <= 1:
        return None
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("batch",))


def maybe_mesh():
    """``get_mesh()`` that never raises: the serve plane's construction-time
    hook — any mesh-resolution failure means the single-device path, with
    the flight recorder (not an exception) carrying the why."""
    if os.environ.get(MESH_ENV, "off").strip().lower() in (
        "", "off", "none", "0", "1",
    ):
        return None  # fast path: no jax import when the mesh is off
    try:
        return get_mesh()
    except Exception:
        return None
