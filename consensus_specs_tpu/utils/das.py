"""DAS sampling: split extended blob data into KZG-proven samples, verify
them individually, reconstruct from any half (reference
specs/das/das-core.md:113-190; draft containers :48-56).

Own implementation over utils/kzg.py. A "sample" here is the draft's
``DASSample`` payload as plain data — (index, proof, points) — since the
draft fork itself is not an executable spec in the reference either.

The verify path is the TPU-relevant one: every sample check is one pairing
product (check_multi_kzg_proof), so a block's worth of samples batches onto
the device exactly like attestation signatures (SURVEY §2.7/P6).
"""
from typing import List, NamedTuple, Optional, Sequence

from . import kzg
from .kzg import MODULUS


class DASSample(NamedTuple):
    index: int
    proof: object  # G1 point (oracle representation)
    data: List[int]  # POINTS_PER_SAMPLE field elements, extended-data order


def sample_data(setup: kzg.Setup, extended_data: Sequence[int],
                points_per_sample: int) -> List[DASSample]:
    """Samples with per-coset multiproofs (das-core.md:128-151)."""
    n = len(extended_data)
    sample_count = n // points_per_sample
    assert sample_count * points_per_sample == n
    # polynomial of the extended data (second half of coefficients zero)
    poly = kzg.inverse_fft(kzg.reverse_bit_order_list(list(extended_data)))
    assert all(c == 0 for c in poly[n // 2:])

    samples = []
    for i in range(sample_count):
        x = _sample_x(n, sample_count, i)
        data = list(extended_data[i * points_per_sample:(i + 1) * points_per_sample])
        proof, ys = kzg.prove_coset(setup, poly, x, points_per_sample)
        # the coset evaluations are exactly the reverse-bit-ordered sample
        assert ys == kzg.reverse_bit_order_list(data)
        samples.append(DASSample(index=i, proof=proof, data=data))
    return samples


def _sample_x(n: int, sample_count: int, index: int) -> int:
    """Coset anchor for sample ``index``.

    Positions [index*pps, (index+1)*pps) of the extended data evaluate the
    polynomial at omega^rbo(index*pps + j, n); writing the n-bit index as
    (index bits | j bits), bit reversal gives exponents
    {rbo(index, sample_count) + k*sample_count}, i.e. the coset of the
    order-pps subgroup anchored at omega^rbo(index, sample_count). (The
    draft's prose here is self-inconsistent — it is marked WIP — so the
    anchor is derived from the ordering actually used by extend_data.)"""
    omega = kzg.root_of_unity(n)
    return pow(omega, kzg.reverse_bit_order(index, sample_count), MODULUS)


def verify_sample(setup: kzg.Setup, sample: DASSample, sample_count: int,
                  commitment) -> bool:
    # (das-core.md:153-162)
    if not 0 <= sample.index < sample_count:
        return False  # reverse_bit_order would alias out-of-range indices
    n = sample_count * len(sample.data)
    x = _sample_x(n, sample_count, sample.index)
    ys = kzg.reverse_bit_order_list(list(sample.data))
    return kzg.check_multi_kzg_proof(setup, commitment, sample.proof, x, ys)


def reconstruct_extended_data(
    samples: Sequence[Optional[DASSample]], sample_count: int,
    points_per_sample: int,
) -> List[int]:
    """Recover the full extended data from >= half the samples
    (das-core.md:164-171)."""
    slots: List[Optional[List[int]]] = [None] * sample_count
    for s in samples:
        if s is not None:
            assert 0 <= s.index < sample_count, "sample index out of range"
            slots[s.index] = list(s.data)
    n = sample_count * points_per_sample
    # map each known point to its NATURAL domain position, recover at the
    # point level (sample boundaries don't align with natural-order chunks),
    # then undo the ordering
    rbo_known: List[Optional[int]] = [None] * n
    for i, sub in enumerate(slots):
        if sub is not None:
            for j, y in enumerate(sub):
                rbo_known[kzg.reverse_bit_order(i * points_per_sample + j, n)] = y
    recovered_natural = kzg.recover_data_points(rbo_known)
    return [recovered_natural[kzg.reverse_bit_order(i, n)] for i in range(n)]
