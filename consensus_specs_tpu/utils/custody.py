"""Custody-game cryptographic primitives (draft fork support).

Own implementation with capability parity to the crypto core of reference
specs/custody_game/beacon-chain.md:258-335: the Legendre-symbol custody
bit over a universal hash of 32-byte data atoms keyed by secrets extracted
from a BLS signature. These are the computable parts the draft's
challenge/response machinery consumes; the epoch-processing scaffolding of
the draft fork follows once the fork is promoted from draft.

The Legendre evaluation over a batch of atoms is an embarrassingly parallel
modular-arithmetic sweep — the same device plane as the field VM if the
custody fork ever needs throughput.
"""
from typing import List, Sequence

from . import bls

# draft constants (custody_game/beacon-chain.md constant tables)
BYTES_PER_CUSTODY_ATOM = 32
CUSTODY_PRIME = 2**256 - 189
CUSTODY_SECRETS = 3
CUSTODY_PROBABILITY_EXPONENT = 10
EPOCHS_PER_CUSTODY_PERIOD = 2**14
CUSTODY_PERIOD_TO_RANDAO_PADDING = 2**11


def legendre_bit(a: int, q: int) -> int:
    """(a/q) Legendre symbol normalized to a bit, via iterative quadratic
    reciprocity (no exponentiation — the draft's prescribed shape)."""
    a %= q
    if a == 0:
        return 0
    assert q > a > 0 and q % 2 == 1
    t = 1
    n = q
    while a != 0:
        while a % 2 == 0:
            a //= 2
            if n % 8 in (3, 5):
                t = -t
        a, n = n, a
        if a % 4 == 3 and n % 4 == 3:
            t = -t
        a %= n
    return (t + 1) // 2 if n == 1 else 0


def get_custody_atoms(bytez: bytes) -> List[bytes]:
    """Right-pad to a whole number of 32-byte atoms and split."""
    pad = (BYTES_PER_CUSTODY_ATOM - len(bytez) % BYTES_PER_CUSTODY_ATOM) % BYTES_PER_CUSTODY_ATOM
    padded = bytes(bytez) + b"\x00" * pad
    return [
        padded[i:i + BYTES_PER_CUSTODY_ATOM]
        for i in range(0, len(padded), BYTES_PER_CUSTODY_ATOM)
    ]


def get_custody_secrets(key: bytes) -> List[int]:
    """Secrets from the x-coordinate of the signature's G2 point: the two
    48-byte Fq2 limbs little-endian-joined, re-chunked into 32-byte ints."""
    ((x_c0, x_c1), _y) = bls.signature_to_G2(key)
    signature_bytes = x_c0.to_bytes(48, "little") + x_c1.to_bytes(48, "little")
    return [
        int.from_bytes(signature_bytes[i:i + BYTES_PER_CUSTODY_ATOM], "little")
        for i in range(0, len(signature_bytes), 32)
    ]


def universal_hash_function(data_chunks: Sequence[bytes], secrets: Sequence[int]) -> int:
    n = len(data_chunks)
    acc = 0
    for i, atom in enumerate(data_chunks):
        acc += (
            pow(secrets[i % CUSTODY_SECRETS], i, CUSTODY_PRIME)
            * int.from_bytes(atom, "little")
        ) % CUSTODY_PRIME
    return (acc + pow(secrets[n % CUSTODY_SECRETS], n, CUSTODY_PRIME)) % CUSTODY_PRIME


def get_randao_epoch_for_custody_period(period: int, validator_index: int) -> int:
    """Epoch whose randao reveal keys a validator's custody period — each
    validator's period boundary is offset by its index, staggering reveals
    (custody_game/beacon-chain.md:336-341)."""
    next_period_start = (
        (period + 1) * EPOCHS_PER_CUSTODY_PERIOD
        - validator_index % EPOCHS_PER_CUSTODY_PERIOD
    )
    return next_period_start + CUSTODY_PERIOD_TO_RANDAO_PADDING


def get_custody_period_for_validator(validator_index: int, epoch: int) -> int:
    """Reveal period covering ``epoch`` for ``validator_index``
    (custody_game/beacon-chain.md:343-350)."""
    return (
        epoch + validator_index % EPOCHS_PER_CUSTODY_PERIOD
    ) // EPOCHS_PER_CUSTODY_PERIOD


def compute_custody_bit(key: bytes, data: bytes) -> int:
    custody_atoms = get_custody_atoms(data)
    secrets = get_custody_secrets(key)
    uhf = universal_hash_function(custody_atoms, secrets)
    bits = [
        legendre_bit(uhf + secrets[0] + i, CUSTODY_PRIME)
        for i in range(CUSTODY_PROBABILITY_EXPONENT)
    ]
    return int(all(bits))
