"""Minimal pure-Python Snappy RAW-format codec.

The reference compresses test-vector SSZ parts with `python-snappy` (a C
binding, reference gen_helpers/gen_base/gen_runner.py:14, 229-235). That
package isn't available here, so this module implements the raw Snappy
block format (github.com/google/snappy/blob/main/format_description.txt)
directly:

- ``compress`` emits a LITERALS-ONLY stream — a valid Snappy encoding any
  conformant decompressor accepts (compression is an encoder freedom, not a
  format requirement; SSZ vectors are small and mostly incompressible
  hashes anyway).
- ``decompress`` implements the full tag set (literals + 1/2/4-byte-offset
  copies) so vectors produced by other toolchains round-trip too.
"""


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def compress(data: bytes) -> bytes:
    data = bytes(data)
    out = bytearray(_uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + (1 << 32) - 1]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out.append(60 << 2)
            out += n.to_bytes(1, "little")
        elif n < (1 << 16):
            out.append(61 << 2)
            out += n.to_bytes(2, "little")
        elif n < (1 << 24):
            out.append(62 << 2)
            out += n.to_bytes(3, "little")
        else:
            out.append(63 << 2)
            out += n.to_bytes(4, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def decompress(data: bytes) -> bytes:
    data = bytes(data)
    # preamble: uncompressed length
    total = 0
    shift = 0
    pos = 0
    while True:
        b = data[pos]
        pos += 1
        total |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            n = tag >> 2
            if n >= 60:
                extra = n - 59
                n = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            n += 1
            out += data[pos : pos + n]
            pos += n
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("snappy: zero copy offset")
        # copies may overlap their own output (run-length behaviour)
        start = len(out) - offset
        if start < 0:
            raise ValueError("snappy: copy before stream start")
        for i in range(length):
            out.append(out[start + i])
    if len(out) != total:
        raise ValueError(f"snappy: length mismatch {len(out)} != {total}")
    return bytes(out)
