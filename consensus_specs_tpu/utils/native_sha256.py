"""ctypes binding for the native batched SHA-256 (csrc/sha256_batch.c).

``hash_pairs(data) -> bytes`` hashes ``len(data)//64`` independent 64-byte
messages in ONE native call — the merkleization inner loop
(utils/merkle_minimal.py, utils/ssz/ssz_typing.py merkleize_chunks) calls it
once per tree layer instead of once per node pair through hashlib.

The shared object is built on demand (`make native`, or lazily here when a
compiler is available); everything falls back to hashlib when it isn't —
the native path is a throughput component, never a correctness dependency.
"""
import ctypes
import hashlib
import subprocess
from pathlib import Path

_REPO = Path(__file__).resolve().parents[2]
_SRC = _REPO / "csrc" / "sha256_batch.c"
_SO = _REPO / "csrc" / "libsha256_batch.so"

_lib = None


def _build() -> bool:
    try:
        subprocess.run(
            ["gcc", "-O3", "-fPIC", "-shared", "-o", str(_SO), str(_SRC)],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not _SO.exists():
        if not (_SRC.exists() and _build()):
            _lib = False
            return _lib
    try:
        lib = ctypes.CDLL(str(_SO))
        lib.sha256_hash_pairs.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.sha256_hash_pairs.restype = None
        _lib = lib
    except OSError:
        _lib = False
    return _lib


def available() -> bool:
    return bool(_load())


def hash_pairs(data: bytes) -> bytes:
    """SHA-256 of each consecutive 64-byte message in ``data``; returns the
    concatenated 32-byte digests."""
    n, rem = divmod(len(data), 64)
    assert rem == 0, "hash_pairs input must be a whole number of 64-byte pairs"
    lib = _load()
    if not lib:
        out = bytearray()
        for i in range(n):
            out += hashlib.sha256(data[64 * i: 64 * (i + 1)]).digest()
        return bytes(out)
    buf = ctypes.create_string_buffer(32 * n)
    lib.sha256_hash_pairs(data, buf, n)
    return buf.raw
