"""ctypes binding for the native batched SHA-256 (csrc/sha256_batch.c).

``hash_pairs(data) -> bytes`` hashes ``len(data)//64`` independent 64-byte
messages in ONE native call — the merkleization inner loop
(utils/merkle_minimal.py, utils/ssz/ssz_typing.py merkleize_chunks) calls it
once per tree layer instead of once per node pair through hashlib.

``hash_many(messages) -> list[bytes]`` hashes a batch of VARIABLE-length
messages in one native call — the expand_message_xmd rounds of the batched
hash-to-G2 codec (consensus_specs_tpu/ops/codec.py) call it once per XMD
round instead of once per message.

The shared object is built on demand (`make native`, or lazily here when a
compiler is available); a stale .so predating ``sha256_hash_many`` is
rebuilt once. Everything falls back to hashlib when no compiler exists —
the native path is a throughput component, never a correctness dependency.
"""
import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import List, Sequence

_REPO = Path(__file__).resolve().parents[2]
_SRC = _REPO / "csrc" / "sha256_batch.c"
_SO = _REPO / "csrc" / "libsha256_batch.so"

_lib = None
_has_many = False


def _build() -> bool:
    """Compile to a temp path, then os.replace onto the final name: the
    rename gives the .so a fresh inode, so processes still mapping the
    OLD library keep their (old-inode) text pages intact, and a re-CDLL
    of the path resolves to the new dev/ino instead of the stale cached
    handle. Compiling straight onto the dlopened path would truncate a
    live mapping (SIGBUS / garbage instructions on the next call)."""
    tmp = _SO.with_suffix(".so.%d.tmp" % os.getpid())
    try:
        subprocess.run(
            ["gcc", "-O3", "-fPIC", "-shared", "-o", str(tmp), str(_SRC)],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except Exception:
        tmp.unlink(missing_ok=True)
        return False


def _bind(lib) -> bool:
    """Declare signatures; returns whether the hash_many symbol exists."""
    global _has_many
    lib.sha256_hash_pairs.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.sha256_hash_pairs.restype = None
    try:
        lib.sha256_hash_many.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.sha256_hash_many.restype = None
        _has_many = True
    except AttributeError:
        _has_many = False
    return _has_many


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not _SO.exists():
        if not (_SRC.exists() and _build()):
            _lib = False
            return _lib
    try:
        lib = ctypes.CDLL(str(_SO))
        if not _bind(lib) and _SRC.exists() and _build():
            # stale .so from before sha256_hash_many: rebuilt — reload
            lib = ctypes.CDLL(str(_SO))
            _bind(lib)
        _lib = lib
    except OSError:
        _lib = False
    return _lib


def available() -> bool:
    return bool(_load())


def hash_pairs(data: bytes) -> bytes:
    """SHA-256 of each consecutive 64-byte message in ``data``; returns the
    concatenated 32-byte digests."""
    n, rem = divmod(len(data), 64)
    assert rem == 0, "hash_pairs input must be a whole number of 64-byte pairs"
    lib = _load()
    if not lib:
        out = bytearray()
        for i in range(n):
            out += hashlib.sha256(data[64 * i: 64 * (i + 1)]).digest()
        return bytes(out)
    buf = ctypes.create_string_buffer(32 * n)
    lib.sha256_hash_pairs(data, buf, n)
    return buf.raw


def hash_many(messages: Sequence[bytes]) -> List[bytes]:
    """SHA-256 of each (variable-length) message, one native call for the
    whole batch; hashlib fallback when the native symbol is unavailable."""
    n = len(messages)
    if n == 0:
        return []
    lib = _load()
    if not lib or not _has_many:
        return [hashlib.sha256(m).digest() for m in messages]
    lens = (ctypes.c_uint64 * n)(*[len(m) for m in messages])
    out = ctypes.create_string_buffer(32 * n)
    lib.sha256_hash_many(b"".join(messages), lens, out, n)
    raw = out.raw
    return [raw[32 * i : 32 * (i + 1)] for i in range(n)]
