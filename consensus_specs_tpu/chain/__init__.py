"""Chain plane: incremental proto-array fork choice behind the streaming
verifier.

``proto_array``   the spec-agnostic incremental LMD-GHOST index (weight
                  deltas, one reverse sweep per batch, O(1) head);
``head_service``  gossip ingestion wired to the spec Store (oracle) and a
                  serve-plane ``VerificationService`` (signatures);
``metrics``       the ``chain.*`` observability family.
"""
from .head_service import HeadService
from .metrics import ChainMetrics
from .proto_array import ProtoArray, ProtoForkChoice

__all__ = [
    "HeadService",
    "ChainMetrics",
    "ProtoArray",
    "ProtoForkChoice",
]
