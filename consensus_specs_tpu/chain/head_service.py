"""HeadService: gossip in, O(1) ``get_head()`` out.

This is the subsystem the serve plane was missing a consumer for: the
streaming verifier (``serve/service.py``) can sustain gossip-rate
signature checks, but verified attestations went nowhere. HeadService
closes the loop:

  on_block / on_attestations (gossip) ──> structural validation against
  the spec Store ──> signature checks routed through a
  ``VerificationService`` (micro-batched, deduped, RLC-combined) ──>
  verified latest-message updates applied to BOTH the spec ``Store``
  (the oracle) and the incremental proto-array (the production path)
  ──> one reverse sweep per batch ──> ``get_head()`` reads a pointer.

The spec ``Store`` is not a shadow — it IS the state source: blocks run
the real ``spec.on_block`` (state transition, checkpoint promotion),
attestations run the real validation pipeline with exactly one
substitution: ``is_valid_indexed_attestation``'s BLS check goes through
the verification service instead of inline crypto. The proto-array is a
derived index over that store, which is what makes the differential gate
meaningful: ``spec.get_head(store)`` recomputed from scratch must equal
the maintained pointer after every mutation batch
(``differential=True`` / ``CONSENSUS_SPECS_TPU_CHAIN_DIFF=1`` asserts it
inline; tests/test_chain*.py gate it).

Gossip reality is handled the way real clients do:
- attestations for **unknown blocks** or **future slots/epochs** are
  parked in a bounded deferral buffer keyed by the MISSING DEPENDENCY
  and retried when that dependency can resolve: a block arrival retries
  only the entries whose missing root is now known, a clock tick retries
  everything (time is a trigger for every defer reason). Unrelated block
  arrivals never consume an entry's retry budget — under simulated
  reordering (sim/), an attestation heard before its target block must
  survive arbitrarily many interleaved third-party blocks and still
  apply when its own block finally lands, whatever the delivery order
  ("delay consideration", fork-choice.md);
- attestations with **invalid signatures**, inconsistent FFG/LMD votes,
  or malformed committees are dropped and counted;
- everything observable exports through ``chain.*`` metrics
  (obs/registry.py) and per-batch spans (validate / sig_wait / apply /
  sweep / head) on the request tracer when tracing is enabled; gossip
  items arriving with a birth record (``obs/latency.py``) additionally
  land their end-to-end gossip→head latency in the
  ``latency.gossip_to_head`` histogram at the head stage.

Threading contract: one mutator at a time (a gossip loop), matching the
spec Store's own single-writer shape. Reads (``get_head``,
``head_slot``) are plain attribute loads.
"""
import os
import time
from collections import deque
from typing import List, Optional, Tuple

from ..obs import flight, latency, tracing
from .metrics import ChainMetrics
from .proto_array import ProtoForkChoice

DIFF_ENV = "CONSENSUS_SPECS_TPU_CHAIN_DIFF"
# speculative head application (ISSUE 12): apply a batch's latest-message
# updates to the proto-array BEFORE the signature verdicts return, and
# roll back (exact weight-delta reversal) if any verdict fails — the head
# answers with the new votes a whole sig_wait earlier, and the RLC
# bisection already localizes any liar the rollback then unwinds
SPECULATE_ENV = "CONSENSUS_SPECS_TPU_SPECULATE"

# attestation routing verdicts (metrics buckets + deferral control)
OK, DEFER, DROP = "ok", "defer", "drop"


def _cp(checkpoint) -> Tuple[int, bytes]:
    return (int(checkpoint.epoch), bytes(checkpoint.root))


class _Verdict:
    """Future-shaped immediate result (the no-service verification path)."""

    __slots__ = ("_value",)

    def __init__(self, value: bool):
        self._value = value

    def result(self, timeout=None) -> bool:
        return self._value


class _Prepared:
    __slots__ = ("attestation", "indices", "future", "birth")

    def __init__(self, attestation, indices, future, birth=None):
        self.attestation = attestation
        self.indices = indices
        self.future = future
        # obs.latency.Birth from gossip ingress (or None): the end-to-end
        # gossip→head timeline's origin and Chrome flow id
        self.birth = birth


class HeadService:
    """Incremental fork choice behind the streaming verifier.

    ``spec`` is a built spec module; ``anchor_state``/``anchor_block``
    boot the store exactly like ``spec.get_forkchoice_store``. ``service``
    is a ``serve.VerificationService`` (or None: signatures verify
    through the spec's own BLS switchboard, honoring ``bls_active``).
    """

    def __init__(self, spec, anchor_state, anchor_block, *, service=None,
                 metrics: Optional[ChainMetrics] = None, tracer=None,
                 differential: Optional[bool] = None,
                 max_deferred: int = 4096, defer_retries: int = 8,
                 verify_timeout: float = 120.0, node: Optional[str] = None,
                 recorder=None, speculative: Optional[bool] = None):
        self.spec = spec
        self.node = node
        self.store = spec.get_forkchoice_store(anchor_state, anchor_block)
        self._service = service
        # `node` labels the whole metric family (chain[<node>].<name>) so
        # N instances — one per simnet node — coexist in one process
        self.metrics = metrics or ChainMetrics(node=node)
        self._tracer = tracer if tracer is not None else tracing.maybe_tracer()
        # flight recorder (obs/flight.py): chain-plane forensics — block
        # arrivals, deferrals, drops, prunes. An explicit per-instance
        # recorder wins (simnet hands each node its own journal);
        # otherwise the env-gated global one. None when disabled; every
        # site guards on `is not None` (the tracer's zero-cost contract)
        self._flight = recorder if recorder is not None \
            else flight.maybe_recorder()
        if differential is None:
            differential = os.environ.get(DIFF_ENV, "0") not in ("", "0")
        self._differential = differential
        if speculative is None:
            speculative = os.environ.get(SPECULATE_ENV, "0") not in ("", "0")
        # speculation needs an async verdict source to hide latency
        # behind; with the inline _Verdict path the verdicts are already
        # in hand before any apply could speculate
        self._speculative = bool(speculative) and service is not None
        self._max_deferred = max_deferred
        self._defer_retries = defer_retries
        self._verify_timeout = verify_timeout
        # (attestation, attempts, missing, birth) — `missing` is the
        # block root the entry is waiting on, or None for time-gated
        # defers (future slot/epoch); `birth` is the item's gossip-ingress
        # record (obs/latency.py) so a deferred-then-resolved attestation
        # still reports its TRUE gossip→head latency, deferral included.
        # Attempts only tick when the entry's own trigger fired and it
        # STILL re-deferred, never on unrelated arrivals.
        self._deferred: "deque[Tuple[object, int, object, object]]" = deque()

        self.fc = ProtoForkChoice()
        anchor_root = bytes(spec.hash_tree_root(anchor_block))
        anchor_state_stored = self.store.block_states[
            spec.hash_tree_root(anchor_block)
        ]
        self.fc.on_block(
            anchor_root, None, int(anchor_block.slot),
            _cp(anchor_state_stored.current_justified_checkpoint),
            _cp(anchor_state_stored.finalized_checkpoint),
        )
        self._cp_key = None
        self._refresh_checkpoints()
        self.fc.apply()
        self._head = self.fc.head()
        self._head_slot = int(anchor_block.slot)
        self.metrics.note_head(int(anchor_block.slot), changed=False,
                               reorg_depth=0)
        self.metrics.export_gauges(tracked_blocks=self.fc.block_count)

    # -- reading -------------------------------------------------------------

    def get_head(self):
        """The maintained head root, O(1). Bit-identical to
        ``spec.get_head(store)`` — the differential gate's claim."""
        return self.spec.Root(self._head)

    @property
    def head_slot(self) -> int:
        # cached next to the head pointer (NOT derived through the array:
        # between a pruning refresh and the batch's head update the old
        # head may be untracked, and readers must stay plain loads)
        return self._head_slot

    @property
    def deferred_count(self) -> int:
        return len(self._deferred)

    # -- gossip ingress ------------------------------------------------------

    def on_tick(self, time_: int) -> None:
        """Clock advance; may promote the justified checkpoint (epoch
        boundary) and unlock deferred future-slot attestations."""
        before = self.spec.get_current_slot(self.store)
        self.spec.on_tick(self.store, self.spec.uint64(int(time_)))
        slot_advanced = self.spec.get_current_slot(self.store) != before
        checkpoint_moved = self._refresh_checkpoints()
        retry = []
        if slot_advanced and self._deferred:
            # time moved: every defer reason is re-examinable (future
            # slots unlock, stale epochs become droppable). Only
            # TIME-gated entries are charged a retry attempt — a
            # block-gated entry's trigger is its missing root, so ticks
            # re-route it uncharged (stale-epoch eviction still applies)
            retry = [(att, attempts, missing is None, birth)
                     for att, attempts, missing, birth in self._deferred]
            self._deferred.clear()
        if retry or checkpoint_moved:
            self._ingest_batch([], retries=retry)

    def _take_resolved_deferred(self) -> list:
        """Deferred entries whose missing dependency is now in the store
        — the ONLY entries a block arrival may retry (charged: their
        trigger fired). Entries waiting on a still-unknown root (or on
        the clock) stay parked with their retry budget untouched, which
        is what makes the buffer's outcome independent of the order
        unrelated blocks arrive in."""
        if not self._deferred:
            return []
        resolved, keep = [], deque()
        for att, attempts, missing, birth in self._deferred:
            if missing is not None and missing in self.store.blocks:
                resolved.append((att, attempts, True, birth))
            else:
                keep.append((att, attempts, missing, birth))
        self._deferred = keep
        return resolved

    def on_block(self, signed_block, process_attestations: bool = True) -> None:
        """Full spec ``on_block`` (state transition included), then the
        proto-array insert and one batch apply covering the block body's
        attestations plus any deferred gossip the new block resolves.
        Invalid blocks raise exactly as the spec does — and leave both
        the store and the array untouched."""
        spec, store = self.spec, self.store
        spec.on_block(store, signed_block)  # raises on invalid
        block = signed_block.message
        root = spec.hash_tree_root(block)
        state = store.block_states[root]
        self.fc.on_block(
            bytes(root), bytes(block.parent_root), int(block.slot),
            _cp(state.current_justified_checkpoint),
            _cp(state.finalized_checkpoint),
        )
        self.metrics.note_block()
        if self._flight is not None:
            self._flight.note("chain", "on_block", slot=int(block.slot),
                              root=bytes(root).hex()[:16],
                              deferred_pending=len(self._deferred))
        self._refresh_checkpoints()
        batch = list(block.body.attestations) if process_attestations else []
        self._ingest_batch(batch, retries=self._take_resolved_deferred())

    def on_attestation(self, attestation, birth=None) -> dict:
        return self.on_attestations([attestation],
                                    births=None if birth is None
                                    else [birth])

    def on_attestations(self, attestations, births=None) -> dict:
        """One gossip micro-batch: validate → verify (batched through the
        service) → apply → one sweep. Returns the routing summary.

        ``births`` (optional, aligned with ``attestations``; entries may
        be None) carries each item's gossip-ingress record
        (``obs/latency.birth()``): the end-to-end gossip→head latency is
        then recorded per item at the head update that reflects its vote,
        and the serve/chain span trees link by Chrome flow id."""
        return self._ingest_batch(list(attestations), births=births)

    # -- pipeline ------------------------------------------------------------

    def _classify(self, attestation) -> Tuple[str, object]:
        """The spec's ``validate_on_attestation`` checks, split into
        "apply now" / "delay consideration" (the spec's own wording for
        unknown blocks and future slots/epochs) / "never valid". Returns
        ``(verdict, missing)``: for DEFER, ``missing`` is the unknown
        block root the entry waits on, or None when only the clock gates
        it — the key the deferral buffer retries on."""
        spec, store = self.spec, self.store
        data = attestation.data
        target = data.target
        current_epoch = spec.compute_epoch_at_slot(spec.get_current_slot(store))
        previous_epoch = (current_epoch - 1 if current_epoch > spec.GENESIS_EPOCH
                          else spec.GENESIS_EPOCH)
        if target.epoch not in (current_epoch, previous_epoch):
            return (DEFER, None) if target.epoch > current_epoch \
                else (DROP, None)
        if target.epoch != spec.compute_epoch_at_slot(data.slot):
            return DROP, None
        if target.root not in store.blocks:
            return DEFER, target.root
        if data.beacon_block_root not in store.blocks:
            return DEFER, data.beacon_block_root
        if store.blocks[data.beacon_block_root].slot > data.slot:
            return DROP, None
        target_slot = spec.compute_start_slot_at_epoch(target.epoch)
        if target.root != spec.get_ancestor(store, data.beacon_block_root,
                                            target_slot):
            return DROP, None
        if spec.get_current_slot(store) < data.slot + 1:
            return DEFER, None
        return OK, None

    def _prepare(self, attestation, birth=None) -> Optional[_Prepared]:
        """Index the attestation against its target checkpoint state and
        submit the signature check. Returns None for structurally invalid
        committees (the spec's non-crypto ``is_valid_indexed_attestation``
        half)."""
        spec, store = self.spec, self.store
        target = attestation.data.target
        try:
            spec.store_target_checkpoint_state(store, target)
            target_state = store.checkpoint_states[target]
            indexed = spec.get_indexed_attestation(target_state, attestation)
        except Exception:
            return None  # malformed committee coordinates
        indices = list(indexed.attesting_indices)
        if not indices or indices != sorted(set(indices)):
            return None
        pubkeys = [target_state.validators[i].pubkey for i in indices]
        domain = spec.get_domain(target_state, spec.DOMAIN_BEACON_ATTESTER,
                                 target.epoch)
        signing_root = bytes(spec.compute_signing_root(indexed.data, domain))
        signature = bytes(attestation.signature)
        if self._service is not None:
            if birth is not None:
                # thread the ingress record through the serve plane: the
                # request trace gains the ingress span and the Chrome
                # flow id that links it to this chain batch
                future = self._service.submit(
                    "fast_aggregate", pubkeys, signing_root, signature,
                    birth_s=birth.t, flow_id=birth.trace_id)
            else:
                future = self._service.submit("fast_aggregate", pubkeys,
                                              signing_root, signature)
        else:
            future = _Verdict(bool(spec.bls.FastAggregateVerify(
                pubkeys, signing_root, signature)))
        return _Prepared(attestation, indices, future, birth=birth)

    def _speculate_item(self, item: _Prepared) -> Tuple[list, int]:
        """Apply one prepared item's latest messages to the PROTO ARRAY
        only, capturing undo tokens (the spec store — the oracle — is
        never speculated on). Returns ``(tokens, moved)``."""
        att = item.attestation
        target_epoch = int(att.data.target.epoch)
        root = bytes(att.data.beacon_block_root)
        tokens, moved = [], 0
        for i in item.indices:
            applied, token = self.fc.speculate_latest_message(
                int(i), root, target_epoch)
            if applied:
                moved += 1
                tokens.append(token)
        return tokens, moved

    def _ingest_batch(self, attestations: List, retries: List = (),
                      births: Optional[List] = None) -> dict:
        """The per-batch pipeline shared by every ingress path. ``retries``
        carries ``(attestation, attempts, charge, birth)`` deferral
        entries riding along — ``charge`` says whether this retry counts
        against the entry's budget (its own trigger fired) or is
        incidental (a tick re-examining a block-gated entry for
        staleness). ``births`` aligns with ``attestations`` (entries may
        be None): the gossip-ingress records the end-to-end latency plane
        stitches from.

        With speculation armed (``CONSENSUS_SPECS_TPU_SPECULATE`` /
        ``speculative=``), the batch's latest messages land on the
        proto-array BEFORE the signature verdicts return — ``get_head``
        answers with the new votes a whole sig_wait earlier. Any failed
        verdict rolls the WHOLE speculative batch back (LIFO weight-delta
        reversal, so intra-batch displacement chains unwind exactly) and
        the verified members re-apply on the normal path — the post-batch
        state is bit-identical to never having speculated, which is what
        the differential gates assert."""
        t0 = time.perf_counter()
        trace = None
        if self._tracer is not None:
            trace = self._tracer.begin("chain_apply",
                                       len(attestations) + len(retries), t0)
        summary = {"applied": 0, "stale": 0, "deferred": 0, "dropped": 0,
                   "resolved": 0}
        prepared: List[Tuple[_Prepared, bool]] = []  # (item, was_deferred)

        def route(att, attempts, was_deferred, charge=True, birth=None):
            verdict, missing = self._classify(att)
            if verdict == OK:
                item = self._prepare(att, birth)
                if item is None:
                    summary["dropped"] += 1
                    self.metrics.note_dropped()
                else:
                    prepared.append((item, was_deferred))
            elif verdict == DEFER and attempts < self._defer_retries \
                    and len(self._deferred) < self._max_deferred:
                attempts = attempts + 1 if charge else attempts
                self._deferred.append((att, attempts, missing, birth))
                summary["deferred"] += 1
                self.metrics.note_deferred(len(self._deferred))
                if self._flight is not None:
                    self._flight.note("chain", "defer",
                                      slot=int(att.data.slot),
                                      attempts=attempts,
                                      pending=len(self._deferred))
            else:  # never valid, retries exhausted, or buffer full
                summary["dropped"] += 1
                self.metrics.note_dropped()
                if self._flight is not None:
                    self._flight.note("chain", "drop",
                                      slot=int(att.data.slot),
                                      verdict=verdict)

        if births is None:
            births = [None] * len(attestations)
        elif len(births) != len(attestations):
            # zip would silently drop the tail — a misaligned caller must
            # fail loudly, not diverge from peers that processed the rest
            raise ValueError(
                f"births misaligned: {len(births)} births for "
                f"{len(attestations)} attestations")
        for att, birth in zip(attestations, births):
            route(att, 0, was_deferred=False, birth=birth)
        for att, attempts, charge, birth in retries:
            route(att, attempts, was_deferred=True, charge=charge,
                  birth=birth)
        t1 = time.perf_counter()

        # -- speculative apply (before any verdict is in hand) ---------------
        speculated = False
        spec_tokens: list = []
        spec_moved: dict = {}
        t_spec_head = None
        if self._speculative and prepared:
            for item, _was_deferred in prepared:
                tokens, moved = self._speculate_item(item)
                spec_tokens.extend(tokens)
                spec_moved[id(item)] = moved
            self.fc.apply()
            self._update_head()
            t_spec_head = time.perf_counter()
            speculated = True
            self.metrics.note_speculative(len(prepared))
            if self._flight is not None:
                self._flight.note("chain", "speculative_apply",
                                  items=len(prepared),
                                  votes=len(spec_tokens),
                                  head_slot=self._head_slot)

        # the whole batch's signature checks are in the service's
        # micro-batching pipeline now; collect verdicts
        verified: List[Tuple[_Prepared, bool]] = []
        failed = 0
        for item, was_deferred in prepared:
            try:
                ok = bool(item.future.result(timeout=self._verify_timeout))
            except Exception:
                ok = False  # service backpressure/close counts as a drop
            if ok:
                verified.append((item, was_deferred))
            else:
                failed += 1
                summary["dropped"] += 1
                self.metrics.note_dropped()
                if self._flight is not None:
                    self._flight.note(
                        "chain", "drop",
                        slot=int(item.attestation.data.slot),
                        verdict="bad_signature")
        t2 = time.perf_counter()

        if speculated and failed:
            # a liar in the batch: unwind EVERYTHING this batch put on
            # the array (LIFO, exact), then let the verified members
            # re-apply below exactly as an unspeculated batch would —
            # never surgically keep speculative state around a failure
            reverted = self.fc.rollback_latest_messages(spec_tokens)
            self.metrics.note_rollback()
            if self._flight is not None:
                self._flight.note("chain", "rollback", failed=failed,
                                  reverted=reverted, items=len(prepared))
            speculated = False
            t_spec_head = None

        for item, was_deferred in verified:
            if speculated:
                # proto array already holds this item's votes; mirror
                # them into the spec store (the oracle is only ever fed
                # VERIFIED votes, speculation or not)
                self.spec.update_latest_messages(
                    self.store, item.indices, item.attestation)
                applied = spec_moved.get(id(item), 0)
            else:
                applied = self._apply_latest_messages(item)
            if applied:
                summary["applied"] += applied
                self.metrics.note_applied(applied)
            else:
                summary["stale"] += 1
                self.metrics.note_stale()
            if was_deferred:
                summary["resolved"] += 1
                self.metrics.note_resolved(len(self._deferred))
        t3 = time.perf_counter()

        self.fc.apply()
        self._update_head()
        t4 = time.perf_counter()

        # -- head stage: the end-to-end timeline terminates here --------------
        # an item's gossip→head latency ends at the head update that
        # first reflected its vote: the SPECULATIVE update when the whole
        # batch survived, the post-verdict sweep otherwise
        head_ts = t_spec_head if t_spec_head is not None else t4
        flows = []
        for item, _was_deferred in verified:
            if item.birth is not None:
                latency.note_gossip_to_head(max(0.0, head_ts - item.birth.t))
                flows.append(item.birth.trace_id)
        t5 = time.perf_counter()

        self.metrics.note_batch(t5 - t0)
        self.metrics.export_gauges(tracked_blocks=self.fc.block_count)
        latency.note_stage("validate", t1 - t0)
        latency.note_stage("sig_wait", t2 - t1)
        latency.note_stage("apply", t3 - t2)
        latency.note_stage("sweep", t4 - t3)
        latency.note_stage("head", t5 - t4)
        if trace is not None:
            self._tracer.span(trace, "validate", t0, t1)
            self._tracer.span(trace, "sig_wait", t1, t2)
            self._tracer.span(trace, "apply", t2, t3)
            self._tracer.span(trace, "sweep", t3, t4)
            self._tracer.span(trace, "head", t4, t5)
            trace.flows = tuple(flows)
            self._tracer.finish(trace, True, t5)
        if self._differential:
            self._assert_spec_head()
        return summary

    def _apply_latest_messages(self, item: _Prepared) -> int:
        """Mirror ``spec.update_latest_messages`` into both tables; returns
        how many validators' latest messages actually moved."""
        att = item.attestation
        target_epoch = int(att.data.target.epoch)
        root = bytes(att.data.beacon_block_root)
        moved = 0
        for i in item.indices:
            if self.fc.on_latest_message(int(i), root, target_epoch):
                moved += 1
        self.spec.update_latest_messages(self.store, item.indices, att)
        return moved

    def _refresh_checkpoints(self) -> bool:
        """Sync the array's viability/balance inputs with the store's
        (possibly just-moved) justified/finalized checkpoints."""
        spec, store = self.spec, self.store
        jc, fin = store.justified_checkpoint, store.finalized_checkpoint
        key = (_cp(jc), _cp(fin))
        if key == self._cp_key:
            return False
        # the balance source the spec's weight sum reads; materialize it
        # if no attestation has targeted this checkpoint yet (the spec's
        # own get_head needs the same entry to exist)
        spec.store_target_checkpoint_state(store, jc)
        state = store.checkpoint_states[jc]
        active = spec.get_active_validator_indices(
            state, spec.get_current_epoch(state))
        balances = {
            int(i): int(state.validators[i].effective_balance) for i in active
        }
        pruned = self.fc.update_checkpoints(_cp(jc), _cp(fin), balances)
        if pruned:
            self.metrics.note_pruned(pruned)
            if self._flight is not None:
                self._flight.note("chain", "prune", nodes=pruned,
                                  finalized_epoch=_cp(fin)[0])
        self._cp_key = key
        return True

    def _update_head(self) -> None:
        new_head = self.fc.head()
        if new_head == self._head:
            self.metrics.note_head(self._head_slot, changed=False,
                                   reorg_depth=0)
            return
        depth = self.fc.array.reorg_depth(self._head, new_head)
        self._head = new_head
        self._head_slot = self.fc.array.node(new_head).slot
        self.metrics.note_head(self._head_slot, changed=True,
                               reorg_depth=depth)

    def _assert_spec_head(self) -> None:
        spec_head = bytes(self.spec.get_head(self.store))
        if spec_head != self._head:
            raise AssertionError(
                "proto-array head diverged from the spec oracle: "
                f"proto={self._head.hex()[:16]} spec={spec_head.hex()[:16]} "
                f"(blocks={self.fc.block_count}, "
                f"justified={self.store.justified_checkpoint.epoch})"
            )

    # -- synthetic replay ----------------------------------------------------

    def import_block_unchecked(self, block, state=None,
                               resolve: bool = False) -> None:
        """Replay/bench ingress: register a block WITHOUT running the state
        transition (the synthetic fork replays in ``bench/head_replay.py``
        build trees whose states are crafted, not computed). Never use on
        a live store — ``on_block`` is the validated path. ``resolve``
        additionally retries the deferred gossip this arrival can resolve
        and sweeps (a block arrival on the validated path always does);
        bulk imports leave it off and call ``resweep()`` once."""
        spec, store = self.spec, self.store
        root = spec.hash_tree_root(block)
        if root in store.blocks:
            return
        store.blocks[root] = block
        if state is not None:
            store.block_states[root] = state
            cps = (_cp(state.current_justified_checkpoint),
                   _cp(state.finalized_checkpoint))
        else:
            cps = (_cp(store.justified_checkpoint),
                   _cp(store.finalized_checkpoint))
        self.fc.on_block(bytes(root), bytes(block.parent_root),
                         int(block.slot), *cps)
        self.metrics.note_block()
        if self._flight is not None:
            self._flight.note("chain", "on_block", slot=int(block.slot),
                              root=bytes(root).hex()[:16],
                              deferred_pending=len(self._deferred))
        if resolve:
            self._ingest_batch([], retries=self._take_resolved_deferred())

    def resweep(self) -> None:
        """Force one sweep + head refresh (after bulk unchecked imports)."""
        self.fc.apply()
        self._update_head()
        self.metrics.export_gauges(tracked_blocks=self.fc.block_count)
