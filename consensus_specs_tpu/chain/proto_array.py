"""Incremental proto-array LMD-GHOST: the chain plane's head index.

The spec's ``get_head`` (specsrc/phase0/fork_choice.py) recomputes the
whole fork choice on every call — ``filter_block_tree`` walks the block
tree re-deriving leaf viability, and every step of the greedy descent
re-sums ``get_latest_attesting_balance`` over all validators: O(blocks ×
validators) per query. Correct as a spec, useless as a serving path. This
module keeps the same answer *incrementally*, the proto-array shape
production clients use:

- nodes live in one flat list in **insertion order**, which is a
  topological order (a block's parent is always known before the block —
  ``on_block`` guarantees it), so "children before parents" is simply a
  reverse iteration;
- each latest-message change contributes a **weight delta** (+balance at
  the new vote root, −balance at the old one); deltas accumulate between
  batches and one **reverse sweep** per batch propagates them to every
  ancestor while recomputing best-child/best-descendant pointers;
- ``head()`` is then a single pointer read: the justified node's
  best-descendant.

Exactness over speed tricks: the spec filters the tree by **leaf**
viability (``filter_block_tree`` checks the leaf state's
justified/finalized checkpoints and includes an interior node iff any
descendant leaf agrees with the store) — NOT by per-node viability as
some production proto-arrays do. The sweep therefore computes
``subtree_viable`` bottom-up from actual leaves, and the differential
gate (tests/test_chain.py) holds the result bit-identical to
``spec.get_head`` after every mutation batch.

This layer is spec-agnostic on purpose: roots are ``bytes``, checkpoints
are ``(epoch, root)`` tuples, balances are plain ints. The spec-facing
glue (``head_service.py``) normalizes.
"""
from typing import Dict, List, Optional, Tuple

Checkpoint = Tuple[int, bytes]  # (epoch, root); epoch 0 == genesis wildcard

GENESIS_EPOCH = 0


class ProtoNode:
    __slots__ = (
        "root", "parent", "slot",
        "justified_checkpoint", "finalized_checkpoint",
        "weight", "child_count", "best_child", "best_descendant",
        "subtree_viable",
    )

    def __init__(self, root: bytes, parent: Optional[int], slot: int,
                 justified_checkpoint: Checkpoint,
                 finalized_checkpoint: Checkpoint):
        self.root = root
        self.parent = parent  # index into the node list, None for the anchor
        self.slot = slot
        # the block's own post-state checkpoints, frozen at insertion —
        # what the spec's leaf-viability test reads off head_state
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        self.weight = 0          # subtree LMD weight (after the last sweep)
        self.child_count = 0
        self.best_child = None   # index of the winning viable child
        self.best_descendant = None  # index of the head within this subtree
        self.subtree_viable = False


class ProtoArray:
    """The node store + the one-sweep maintenance pass."""

    def __init__(self):
        self._nodes: List[ProtoNode] = []
        self._index: Dict[bytes, int] = {}
        self._deltas: List[int] = []

    # -- reading -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, root: bytes) -> bool:
        return root in self._index

    def node(self, root: bytes) -> ProtoNode:
        return self._nodes[self._index[root]]

    def head(self, justified_root: bytes) -> bytes:
        """O(1): the justified node's best-descendant pointer (itself when
        no viable subtree exists — the spec walk then also stops at the
        justified root immediately)."""
        node = self._nodes[self._index[justified_root]]
        if node.best_descendant is None:
            return node.root
        return self._nodes[node.best_descendant].root

    def reorg_depth(self, old_head: bytes, new_head: bytes) -> int:
        """Slots rolled back by a head move: old head's slot minus the
        common ancestor's. 0 for plain extensions (old head is an
        ancestor of the new one) and for heads no longer tracked."""
        ia = self._index.get(old_head)
        ib = self._index.get(new_head)
        if ia is None or ib is None:
            return 0
        old_slot = self._nodes[ia].slot
        # insertion order is topological: an ancestor always has the
        # smaller index, so walking the larger index up converges on the
        # common ancestor
        while ia != ib:
            if ia > ib:
                ia = self._nodes[ia].parent
            else:
                ib = self._nodes[ib].parent
            if ia is None or ib is None:
                return 0
        return max(0, old_slot - self._nodes[ia].slot)

    def ancestor_at_or_below(self, root: bytes, slot: int) -> Optional[bytes]:
        """First ancestor (or self) with node.slot <= slot — the spec's
        ``get_ancestor`` skip-slot rule, answered from the array."""
        i = self._index.get(root)
        while i is not None:
            n = self._nodes[i]
            if n.slot <= slot:
                return n.root
            i = n.parent
        return None

    # -- mutation ------------------------------------------------------------

    def insert(self, root: bytes, parent_root: Optional[bytes], slot: int,
               justified_checkpoint: Checkpoint,
               finalized_checkpoint: Checkpoint) -> None:
        """Add one block. The parent must already be present (matching the
        on_block contract), except for the anchor. Duplicate inserts are
        no-ops (gossip re-delivers blocks)."""
        if root in self._index:
            return
        parent = None
        if parent_root is not None and parent_root in self._index:
            parent = self._index[parent_root]
            self._nodes[parent].child_count += 1
        elif self._nodes:
            raise KeyError(f"unknown parent {parent_root!r} for {root!r}")
        self._index[root] = len(self._nodes)
        self._nodes.append(ProtoNode(root, parent, int(slot),
                                     justified_checkpoint,
                                     finalized_checkpoint))
        self._deltas.append(0)

    def add_delta(self, root: bytes, amount: int) -> None:
        """Queue a weight change at ``root`` for the next sweep. Unknown
        roots swallow silently: a vote whose block was pruned can no
        longer influence any tracked subtree."""
        i = self._index.get(root)
        if i is not None:
            self._deltas[i] += amount

    def apply(self, justified: Checkpoint, finalized: Checkpoint) -> None:
        """The one reverse sweep: children are visited before parents, so a
        single pass propagates queued weight deltas upward, derives leaf →
        subtree viability, and rebuilds every best-child/best-descendant
        pointer against the CURRENT store checkpoints."""
        nodes, deltas = self._nodes, self._deltas
        j_epoch, j_root = justified
        f_epoch, f_root = finalized
        # per-sweep scratch: best (weight, root, index) among viable
        # children seen so far, and whether any viable leaf surfaced
        best: List[Optional[Tuple[int, bytes, int]]] = [None] * len(nodes)
        any_viable = [False] * len(nodes)
        for i in range(len(nodes) - 1, -1, -1):
            n = nodes[i]
            if deltas[i]:
                n.weight += deltas[i]
                if n.parent is not None:
                    deltas[n.parent] += deltas[i]
                deltas[i] = 0
            if n.child_count == 0:
                # a LEAF of the full tree: the spec's filter checks the
                # leaf state's checkpoints (epoch 0 acts as a wildcard)
                viable = (
                    (j_epoch == GENESIS_EPOCH
                     or n.justified_checkpoint == (j_epoch, j_root))
                    and (f_epoch == GENESIS_EPOCH
                         or n.finalized_checkpoint == (f_epoch, f_root))
                )
            else:
                viable = any_viable[i]
            n.subtree_viable = viable
            b = best[i]
            if b is None:
                n.best_child = None
                n.best_descendant = None
            else:
                n.best_child = b[2]
                n.best_descendant = nodes[b[2]].best_descendant
                if n.best_descendant is None:
                    n.best_descendant = b[2]
            if n.parent is not None and viable:
                any_viable[n.parent] = True
                pb = best[n.parent]
                # the spec's max(children, key=(weight, root)) tie-break
                if pb is None or (n.weight, n.root) > (pb[0], pb[1]):
                    best[n.parent] = (n.weight, n.root, i)

    def prune(self, finalized_root: bytes) -> int:
        """Drop everything outside the finalized subtree (the spec walk can
        never reach it again: the justified root always descends from the
        finalized root). Returns how many nodes were dropped. Insertion
        (= topological) order is preserved by the rebuild."""
        if finalized_root not in self._index:
            return 0
        keep = [False] * len(self._nodes)
        fin = self._index[finalized_root]
        keep[fin] = True
        for i, n in enumerate(self._nodes):
            if i != fin and n.parent is not None and keep[n.parent]:
                keep[i] = True
        dropped = keep.count(False)
        if dropped == 0:
            return 0
        remap: Dict[int, int] = {}
        nodes: List[ProtoNode] = []
        deltas: List[int] = []
        index: Dict[bytes, int] = {}
        for i, n in enumerate(self._nodes):
            if not keep[i]:
                continue
            remap[i] = len(nodes)
            n.parent = remap.get(n.parent) if n.parent is not None else None
            # pointer fields are rebuilt by the next sweep; clear rather
            # than remap so a pruned best-descendant can never dangle
            n.best_child = None
            n.best_descendant = None
            index[n.root] = len(nodes)
            nodes.append(n)
            deltas.append(self._deltas[i])
        nodes[remap[fin]].parent = None
        self._nodes, self._deltas, self._index = nodes, deltas, index
        return dropped


class ProtoForkChoice:
    """Vote/balance bookkeeping over a :class:`ProtoArray`.

    Owns the latest-message table (validator → (block root, target
    epoch)), the balance set of the justified checkpoint state, and the
    store's current justified/finalized checkpoints. Every mutation
    queues deltas; ``apply()`` runs the single sweep; ``head()`` reads
    the pointer.
    """

    def __init__(self):
        self.array = ProtoArray()
        self._votes: Dict[int, Tuple[bytes, int]] = {}
        self._balances: Dict[int, int] = {}
        self._justified: Checkpoint = (GENESIS_EPOCH, b"")
        self._finalized: Checkpoint = (GENESIS_EPOCH, b"")
        self._justified_root: Optional[bytes] = None

    # -- mutation ------------------------------------------------------------

    def on_block(self, root: bytes, parent_root: Optional[bytes], slot: int,
                 justified_checkpoint: Checkpoint,
                 finalized_checkpoint: Checkpoint) -> None:
        self.array.insert(root, parent_root, slot, justified_checkpoint,
                          finalized_checkpoint)

    def on_latest_message(self, validator: int, root: bytes,
                          epoch: int) -> bool:
        """The spec's latest-message rule: only a strictly newer target
        epoch displaces an existing vote. Returns whether it applied."""
        applied, _token = self.speculate_latest_message(validator, root,
                                                        epoch)
        return applied

    def speculate_latest_message(self, validator: int, root: bytes,
                                 epoch: int):
        """``on_latest_message`` that also returns an undo token — the
        speculative-apply primitive (ISSUE 12): HeadService applies a
        batch's votes BEFORE the signature verdicts return and, on any
        failure, hands the batch's tokens back to
        :meth:`rollback_latest_messages`. The token captures the
        displaced vote (or None), which with the current balance set is
        everything reversal needs. Returns ``(applied, token)``; a vote
        the latest-message rule rejects applies nothing and yields no
        token."""
        prev = self._votes.get(validator)
        if prev is not None and epoch <= prev[1]:
            return False, None
        balance = self._balances.get(validator, 0)
        if prev is not None and balance:
            self.array.add_delta(prev[0], -balance)
        if balance:
            self.array.add_delta(root, balance)
        self._votes[validator] = (root, epoch)
        return True, (validator, prev)

    def rollback_latest_messages(self, tokens) -> int:
        """Reverse a speculative batch: LIFO over ``tokens`` (the order
        they were produced in), each reversal queueing the exact opposite
        weight deltas and restoring the displaced vote — so a validator
        speculated twice in one batch unwinds through its intermediate
        state back to the pre-batch table, bit-identically. Only valid
        while the balance set is unchanged since the speculation (the
        HeadService batch pipeline guarantees it: checkpoint refreshes
        happen between batches, never inside one). Returns the number of
        reversed applications."""
        reversed_n = 0
        for token in reversed([t for t in tokens if t is not None]):
            validator, prev = token
            cur = self._votes.get(validator)
            balance = self._balances.get(validator, 0)
            if balance and cur is not None:
                self.array.add_delta(cur[0], -balance)
            if prev is None:
                self._votes.pop(validator, None)
            else:
                if balance:
                    self.array.add_delta(prev[0], balance)
                self._votes[validator] = prev
            reversed_n += 1
        return reversed_n

    def update_checkpoints(self, justified: Checkpoint, finalized: Checkpoint,
                           balances: Dict[int, int]) -> int:
        """Track a store checkpoint move. The balance set is the justified
        checkpoint state's active effective balances — when it changes,
        every existing vote is re-based (new − old at its vote root) so
        subtree weights stay exact. Finalization advance prunes; returns
        the pruned node count."""
        pruned = 0
        if balances != self._balances:
            for validator, (root, _epoch) in self._votes.items():
                shift = (balances.get(validator, 0)
                         - self._balances.get(validator, 0))
                if shift:
                    self.array.add_delta(root, shift)
            self._balances = dict(balances)
        if (finalized != self._finalized
                and finalized[0] > self._finalized[0]):
            pruned = self.array.prune(finalized[1])
        self._justified, self._finalized = justified, finalized
        self._justified_root = justified[1]
        return pruned

    def apply(self) -> None:
        """One reverse sweep over the array (call once per batch)."""
        self.array.apply(self._justified, self._finalized)

    # -- reading -------------------------------------------------------------

    def head(self) -> bytes:
        assert self._justified_root is not None, "no checkpoints tracked yet"
        return self.array.head(self._justified_root)

    @property
    def votes(self) -> Dict[int, Tuple[bytes, int]]:
        return self._votes

    @property
    def block_count(self) -> int:
        return len(self.array)
