"""Chain-plane observability: head movement, reorg shape, attestation
routing outcomes, and apply-batch latency.

Counters live on the owning :class:`HeadService`; the derived values
export through ``ops/profiling`` (the ``chain.*`` family in
``obs/registry.py``) so ``/metrics`` scrapes and bench JSON lines carry
the chain numbers the same way they carry the serve plane's.

Multi-instance runs (the ``sim/`` plane drives N ``HeadService``
instances in one process) pass ``node=``: every label then exports in
the node-labelled form ``chain[<node>].<name>`` (the ``chain[`` dynamic
family in ``obs/registry.py``) instead of the bare ``chain.*`` name, so
N instances publish side by side instead of overwriting one shared
gauge.
"""
import threading
from typing import Dict, Optional

from ..obs.registry import node_label
from ..ops import profiling

APPLY_LABEL = "chain.apply_batch"

# the gauge family, in export order (the obs drift gate scans this tuple:
# every name must be registered in obs/registry.py and documented in the
# README metric table)
GAUGE_LABELS = (
    "chain.blocks",
    "chain.head_slot",
    "chain.head_changes",
    "chain.reorgs",
    "chain.last_reorg_depth",
    "chain.applied_attestations",
    "chain.deferred_attestations",
    "chain.dropped_attestations",
    "chain.deferred_pending",
    "chain.speculative_applied",
    "chain.rollbacks",
)


class ChainMetrics:
    """Counters for one HeadService instance. ``node`` labels every
    exported metric for multi-instance (simnet) processes."""

    def __init__(self, node: Optional[str] = None):
        self.node = node
        self._apply_label = node_label(APPLY_LABEL, node)
        self._gauge_labels = tuple(
            node_label(label, node) for label in GAUGE_LABELS)
        self._lock = threading.Lock()
        self.blocks = 0
        self.batches = 0
        self.applied = 0       # attestations that updated a latest message
        self.stale = 0         # verified but older than the known vote
        self.deferred = 0      # parked for a missing block / future slot
        self.dropped = 0       # invalid signature / non-viable / overflow
        self.resolved = 0      # deferred entries that later applied
        self.head_changes = 0
        self.reorgs = 0        # head changes that were not simple extensions
        self.last_reorg_depth = 0
        self.head_slot = 0
        self.deferred_pending = 0
        self.pruned_nodes = 0
        # speculative head application (ISSUE 12): attestations applied
        # to the proto-array before their verdicts returned, and batches
        # that had to be reverted (weight-delta reversal) on a failure
        self.speculative_applied = 0
        self.rollbacks = 0

    # -- recording hooks (head_service.py) ----------------------------------

    def note_block(self) -> None:
        with self._lock:
            self.blocks += 1

    def note_applied(self, n: int = 1) -> None:
        with self._lock:
            self.applied += n

    def note_stale(self, n: int = 1) -> None:
        with self._lock:
            self.stale += n

    def note_deferred(self, pending: int) -> None:
        with self._lock:
            self.deferred += 1
            self.deferred_pending = pending

    def note_resolved(self, pending: int, n: int = 1) -> None:
        with self._lock:
            self.resolved += n
            self.deferred_pending = pending

    def note_dropped(self, n: int = 1) -> None:
        with self._lock:
            self.dropped += n

    def note_pruned(self, n: int) -> None:
        with self._lock:
            self.pruned_nodes += n

    def note_speculative(self, n: int = 1) -> None:
        with self._lock:
            self.speculative_applied += n

    def note_rollback(self) -> None:
        with self._lock:
            self.rollbacks += 1

    def note_batch(self, seconds: float) -> None:
        with self._lock:
            self.batches += 1
        profiling.record_latency(self._apply_label, seconds)

    def note_head(self, slot: int, changed: bool, reorg_depth: int) -> None:
        with self._lock:
            self.head_slot = int(slot)
            if changed:
                self.head_changes += 1
            if reorg_depth > 0:
                self.reorgs += 1
                self.last_reorg_depth = reorg_depth

    # -- export --------------------------------------------------------------

    def export_gauges(self, tracked_blocks: int = None) -> None:
        """Publish the chain family into ``profiling.summary()`` (and so
        onto ``/metrics``). Values line up with ``GAUGE_LABELS``."""
        with self._lock:
            values = (
                self.blocks if tracked_blocks is None else tracked_blocks,
                self.head_slot,
                self.head_changes,
                self.reorgs,
                self.last_reorg_depth,
                self.applied,
                self.deferred,
                self.dropped,
                self.deferred_pending,
                self.speculative_applied,
                self.rollbacks,
            )
        for label, value in zip(self._gauge_labels, values):
            profiling.set_gauge(label, value)
        # the chain plane is the merkle plane's highest-rate consumer
        # (per-block state re-roots), so its export also refreshes the
        # process-wide merkle.* counters onto the same surface
        from ..merkle import levels as _merkle_levels

        _merkle_levels.export_gauges()

    def counters(self) -> Dict[str, int]:
        """Plain counter reads (no latency-summary build) — what the
        per-slot health ledger (``chain/health.py``) diffs every slot,
        where ``snapshot()``'s percentile construction would dominate
        the slot's own cost at soak horizons."""
        with self._lock:
            return {
                "blocks": self.blocks,
                "head_changes": self.head_changes,
                "reorgs": self.reorgs,
                "last_reorg_depth": self.last_reorg_depth,
                "head_slot": self.head_slot,
                "deferred_pending": self.deferred_pending,
                "speculative_applied": self.speculative_applied,
                "rollbacks": self.rollbacks,
            }

    def snapshot(self) -> Dict[str, float]:
        lat = profiling.latency_summary().get(self._apply_label, {})
        with self._lock:
            return {
                "blocks": self.blocks,
                "batches": self.batches,
                "applied": self.applied,
                "stale": self.stale,
                "deferred": self.deferred,
                "resolved": self.resolved,
                "dropped": self.dropped,
                "head_changes": self.head_changes,
                "reorgs": self.reorgs,
                "last_reorg_depth": self.last_reorg_depth,
                "head_slot": self.head_slot,
                "deferred_pending": self.deferred_pending,
                "pruned_nodes": self.pruned_nodes,
                "speculative_applied": self.speculative_applied,
                "rollbacks": self.rollbacks,
                "apply_latency": lat,
            }
