"""Per-slot consensus health ledger (the telemetry plane's chain half,
ISSUE 19).

The Beacon-client security review (PAPERS.md) catalogues the slow-burn
failure modes — participation decay, growing finality lag, deferral-
buffer growth, reorg churn — that no point-in-time gauge can catch: each
one looks healthy in any single sample and only shows up as a TREND.
This module computes the consensus-semantic numbers once per slot from
the structures that already exist (the proto-array's vote/balance
tables, the spec store's checkpoints, ``ChainMetrics`` counters) and
exports them as the ``health.*`` gauge family, which the time-series
store (``obs/timeseries.py``) then samples into history:

- **participation_rate** — attesting balance / total balance in the
  proto-array's balance table (the spec's own weighting, so a validator
  set change moves the denominator the same slot it moves fork choice);
- **head_churn** — head pointer moves this slot;
- **reorg_depth** — deepest rollback among this slot's reorgs (0 when
  the head only extended);
- **finality_lag_slots** — current slot minus the finalized checkpoint
  epoch's start slot: THE liveness number, meaningful only measured
  continuously (a healthy chain holds it near 2 epochs);
- **deferral_depth** — deferral-buffer depth (gossip arriving ahead of
  its dependencies);
- **rollback_rate** — speculative batches reverted this slot;
- **unexplained_reorgs** — cumulative reorgs observed OUTSIDE windows
  the caller declared disruption for (``expect_reorgs=``): the soak's
  "zero unexplained reorgs" gate reads this.

``observe_slot`` is cheap (counter reads + two dict sums), so calling it
every simulated slot for thousands of slots is free relative to the
slot's own processing. ``summary()`` + ``evaluate_gate()`` produce the
"HEALTH DIVERGED" state ``tools/bench_compare.py`` gates on.
"""
from collections import deque
from typing import Dict, List, Optional

from ..obs.registry import node_label
from ..ops import profiling

# the gauge family, in export order (the obs drift gate scans this tuple:
# every name must be registered in obs/registry.py and documented in the
# README metric table)
GAUGE_LABELS = (
    "health.participation_rate",
    "health.head_churn",
    "health.reorg_depth",
    "health.finality_lag_slots",
    "health.deferral_depth",
    "health.rollback_rate",
    "health.unexplained_reorgs",
)

# gate defaults (the soak's acceptance thresholds; scenarios with
# declared disruption pass explicit bounds)
DEFAULT_PARTICIPATION_FLOOR = 0.60
DEFAULT_FINALITY_LAG_MAX_SLOTS = 64


class HealthLedger:
    """Per-slot health records for one :class:`HeadService`.

    ``node`` labels the exported family (``health[<node>].<name>``) so N
    simnet instances publish side by side — same contract as
    ``ChainMetrics``. ``window`` bounds the retained per-slot records
    (the TSDB is the long-horizon store; this ring only feeds
    ``summary()``'s extremes, which are tracked cumulatively anyway)."""

    def __init__(self, head_service, *, node: Optional[str] = None,
                 window: int = 4096):
        self._svc = head_service
        self.node = node
        self._labels = tuple(node_label(label, node)
                             for label in GAUGE_LABELS)
        self._records: "deque[Dict]" = deque(maxlen=window)
        self._prev: Optional[Dict] = None
        self.slots_observed = 0
        self.unexplained_reorgs = 0
        self.participation_min: Optional[float] = None
        self.participation_sum = 0.0
        self.finality_lag_max = 0
        self.reorg_depth_max = 0
        self.deferral_depth_max = 0
        self.head_churn_total = 0
        self.reorgs_total = 0
        self.rollbacks_total = 0

    # -- recording -----------------------------------------------------------

    def observe_slot(self, slot: Optional[int] = None,
                     expect_reorgs: bool = False) -> Dict:
        """Compute + record this slot's health row. ``expect_reorgs``
        declares that disruption (a partition heal, an equivocation
        window) makes reorgs explainable right now — reorgs observed
        while it is False accumulate into ``unexplained_reorgs``."""
        svc = self._svc
        spec, store = svc.spec, svc.store
        if slot is None:
            slot = int(spec.get_current_slot(store))
        balances = svc.fc._balances
        total = sum(balances.values())
        voted = sum(balances.get(v, 0) for v in svc.fc.votes)
        participation = (voted / total) if total else 0.0
        fin_epoch = int(store.finalized_checkpoint.epoch)
        fin_slot = int(spec.compute_start_slot_at_epoch(fin_epoch))
        finality_lag = max(0, int(slot) - fin_slot)
        counters = svc.metrics.counters()
        prev = self._prev or {"head_changes": 0, "reorgs": 0,
                              "rollbacks": 0, "last_reorg_depth": 0}
        churn = counters["head_changes"] - prev["head_changes"]
        reorgs = counters["reorgs"] - prev["reorgs"]
        rollbacks = counters["rollbacks"] - prev["rollbacks"]
        reorg_depth = counters["last_reorg_depth"] if reorgs else 0
        self._prev = counters
        if reorgs and not expect_reorgs:
            self.unexplained_reorgs += reorgs
        record = {
            "slot": int(slot),
            "participation_rate": round(participation, 6),
            "head_churn": churn,
            "reorg_depth": reorg_depth,
            "finality_lag_slots": finality_lag,
            "deferral_depth": svc.deferred_count,
            "rollback_rate": rollbacks,
            "unexplained_reorgs": self.unexplained_reorgs,
            "expected_reorgs": bool(expect_reorgs),
        }
        self._records.append(record)
        self.slots_observed += 1
        self.participation_sum += participation
        if (self.participation_min is None
                or participation < self.participation_min):
            self.participation_min = participation
        self.finality_lag_max = max(self.finality_lag_max, finality_lag)
        self.reorg_depth_max = max(self.reorg_depth_max, reorg_depth)
        self.deferral_depth_max = max(self.deferral_depth_max,
                                      record["deferral_depth"])
        self.head_churn_total += churn
        self.reorgs_total += reorgs
        self.rollbacks_total += rollbacks
        self.export_gauges(record)
        return record

    def export_gauges(self, record: Dict) -> None:
        """Publish the latest row onto the profiling surface (and so into
        every TSDB sample). Values line up with ``GAUGE_LABELS``."""
        values = (
            record["participation_rate"],
            record["head_churn"],
            record["reorg_depth"],
            record["finality_lag_slots"],
            record["deferral_depth"],
            record["rollback_rate"],
            record["unexplained_reorgs"],
        )
        for label, value in zip(self._labels, values):
            profiling.set_gauge(label, value)

    # -- reading -------------------------------------------------------------

    def records(self) -> List[Dict]:
        return list(self._records)

    def summary(self) -> Dict:
        """The gate-facing aggregate over every observed slot."""
        n = max(1, self.slots_observed)
        last = self._records[-1] if self._records else None
        return {
            "slots_observed": self.slots_observed,
            "participation_min": round(self.participation_min or 0.0, 6),
            "participation_mean": round(self.participation_sum / n, 6),
            "participation_last": (last["participation_rate"]
                                   if last else 0.0),
            "finality_lag_max": self.finality_lag_max,
            "finality_lag_last": (last["finality_lag_slots"]
                                  if last else 0),
            "reorg_depth_max": self.reorg_depth_max,
            "reorgs_total": self.reorgs_total,
            "unexplained_reorgs": self.unexplained_reorgs,
            "head_churn_total": self.head_churn_total,
            "rollbacks_total": self.rollbacks_total,
            "deferral_depth_max": self.deferral_depth_max,
        }


def aggregate_summaries(summaries: List[Dict]) -> Dict:
    """Fleet/simnet aggregate: the WORST case across nodes per bound
    (min of participation floors, max of lags/depths, sum of reorg
    counts) — the number the gate judges, because one sick node is a
    sick deployment."""
    if not summaries:
        return {"slots_observed": 0, "participation_min": 0.0,
                "participation_mean": 0.0, "participation_last": 0.0,
                "finality_lag_max": 0, "finality_lag_last": 0,
                "reorg_depth_max": 0, "reorgs_total": 0,
                "unexplained_reorgs": 0, "head_churn_total": 0,
                "rollbacks_total": 0, "deferral_depth_max": 0}
    n = len(summaries)
    return {
        "slots_observed": max(s["slots_observed"] for s in summaries),
        "participation_min": round(
            min(s["participation_min"] for s in summaries), 6),
        "participation_mean": round(
            sum(s["participation_mean"] for s in summaries) / n, 6),
        "participation_last": round(
            min(s["participation_last"] for s in summaries), 6),
        "finality_lag_max": max(s["finality_lag_max"] for s in summaries),
        "finality_lag_last": max(s["finality_lag_last"] for s in summaries),
        "reorg_depth_max": max(s["reorg_depth_max"] for s in summaries),
        "reorgs_total": sum(s["reorgs_total"] for s in summaries),
        "unexplained_reorgs": sum(s["unexplained_reorgs"]
                                  for s in summaries),
        "head_churn_total": sum(s["head_churn_total"] for s in summaries),
        "rollbacks_total": sum(s["rollbacks_total"] for s in summaries),
        "deferral_depth_max": max(s["deferral_depth_max"]
                                  for s in summaries),
    }


def evaluate_gate(summary: Dict, *,
                  participation_floor: float = DEFAULT_PARTICIPATION_FLOOR,
                  finality_lag_max_slots: int = DEFAULT_FINALITY_LAG_MAX_SLOTS,
                  max_unexplained_reorgs: int = 0) -> Dict:
    """The "HEALTH DIVERGED" verdict over a (possibly aggregated)
    summary: participation never below the floor, finality lag bounded
    over the WHOLE horizon (monotone-bounded: the max, not the exit
    sample — a lag that grew and recovered still fails a bound it
    crossed), and zero reorgs outside declared disruption windows."""
    reasons = []
    if summary["slots_observed"] <= 0:
        reasons.append("no slots observed")
    if summary["participation_min"] < participation_floor:
        reasons.append(
            f"participation_min {summary['participation_min']:.4f} "
            f"< floor {participation_floor:.4f}")
    if summary["finality_lag_max"] > finality_lag_max_slots:
        reasons.append(
            f"finality_lag_max {summary['finality_lag_max']} "
            f"> bound {finality_lag_max_slots}")
    if summary["unexplained_reorgs"] > max_unexplained_reorgs:
        reasons.append(
            f"unexplained_reorgs {summary['unexplained_reorgs']} "
            f"> allowed {max_unexplained_reorgs}")
    return {
        "ok": not reasons,
        "reasons": reasons,
        "participation_floor": participation_floor,
        "finality_lag_max_slots": finality_lag_max_slots,
        "max_unexplained_reorgs": max_unexplained_reorgs,
        "summary": dict(summary),
    }
