"""SSZ View -> YAML/JSON-friendly plain-data encoder.

Fills the role of reference eth2spec/debug/encode.py:8-41 (own
implementation over this repo's ssz_typing). uints render as strings when
they exceed 64 bits (YAML integer safety), byte types as 0x-hex, containers
as dicts (optionally annotated with per-field hash_tree_roots).
"""
from ..utils.ssz.ssz_typing import (
    Bitlist, Bitvector, ByteList, ByteVector, Container, List, Union, Vector,
    boolean, uint,
)


def encode(value, include_hash_tree_roots=False):
    if isinstance(value, boolean):
        return bool(value)
    if isinstance(value, uint):
        if type(value).TYPE_BYTE_LENGTH > 8:
            return str(int(value))  # too wide for YAML int consumers
        return int(value)
    if isinstance(value, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (Bitvector, Bitlist)):
        return "0x" + value.encode_bytes().hex()
    if isinstance(value, (Vector, List)):
        return [encode(elem, include_hash_tree_roots) for elem in value]
    if isinstance(value, Container):
        out = {}
        for name in value.fields():
            field = getattr(value, name)
            out[name] = encode(field, include_hash_tree_roots)
            if include_hash_tree_roots:
                out[name + "_hash_tree_root"] = "0x" + field.hash_tree_root().hex()
        if include_hash_tree_roots:
            out["hash_tree_root"] = "0x" + value.hash_tree_root().hex()
        return out
    if isinstance(value, Union):
        inner = None if value.value is None else encode(value.value, include_hash_tree_roots)
        return {"selector": int(value.selector), "value": inner}
    raise TypeError(f"cannot encode {type(value)}")
