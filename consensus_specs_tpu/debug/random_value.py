"""Seeded random SSZ object construction for every View type.

Fills the role of reference eth2spec/debug/random_value.py:17-169 (own
implementation over this repo's ssz_typing): six randomization modes plus a
chaos toggle; the ssz_static generator samples every Container subclass of
every built spec with these.
"""
from enum import Enum
from random import Random
from typing import Type

from ..utils.ssz.ssz_typing import (
    Bitlist, Bitvector, ByteList, ByteVector, Container, List, Union, Vector,
    View, boolean, uint,
)

random_mode_names = ("random", "zero", "max", "nil", "one", "lengthy")


class RandomizationMode(Enum):
    mode_random = 0      # random content and lengths
    mode_zero = 1        # zero values everywhere
    mode_max = 2         # max basic values, single-element collections
    mode_nil_count = 3   # empty variable-size collections
    mode_one_count = 4   # single-element collections, random content
    mode_max_count = 5   # limit-length collections, random content

    def to_name(self):
        return random_mode_names[self.value]

    def is_changing(self):
        return self.value in (0, 4, 5)


def _random_bytes(rng: Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def _basic(rng: Random, typ, mode: RandomizationMode):
    if issubclass(typ, boolean):
        if mode == RandomizationMode.mode_zero:
            return typ(False)
        if mode == RandomizationMode.mode_max:
            return typ(True)
        return typ(rng.choice((True, False)))
    width = typ.TYPE_BYTE_LENGTH * 8
    if mode == RandomizationMode.mode_zero:
        return typ(0)
    if mode == RandomizationMode.mode_max:
        return typ((1 << width) - 1)
    return typ(rng.getrandbits(width))


def _collection_length(rng: Random, mode: RandomizationMode, limit: int,
                       max_random: int) -> int:
    if mode == RandomizationMode.mode_nil_count:
        return 0
    if mode == RandomizationMode.mode_one_count:
        return min(1, limit)
    if mode in (RandomizationMode.mode_max_count, RandomizationMode.mode_max):
        return min(limit, max_random) if mode == RandomizationMode.mode_max_count else min(1, limit)
    if mode == RandomizationMode.mode_zero:
        return 0
    return rng.randint(0, min(limit, max_random))


def get_random_ssz_object(rng: Random, typ: Type[View], max_bytes_length: int,
                          max_list_length: int, mode: RandomizationMode,
                          chaos: bool = False) -> View:
    if chaos:
        mode = rng.choice(list(RandomizationMode))

    if issubclass(typ, ByteVector):
        if mode == RandomizationMode.mode_zero:
            return typ(b"\x00" * typ.LENGTH)
        if mode == RandomizationMode.mode_max:
            return typ(b"\xff" * typ.LENGTH)
        return typ(_random_bytes(rng, typ.LENGTH))
    if issubclass(typ, ByteList):
        n = _collection_length(rng, mode, typ.LIMIT, max_bytes_length)
        fill = (b"\xff" if mode == RandomizationMode.mode_max else None)
        return typ(fill * n if fill else _random_bytes(rng, n))
    if issubclass(typ, Bitvector):
        if mode == RandomizationMode.mode_zero:
            return typ([False] * typ.LENGTH)
        if mode == RandomizationMode.mode_max:
            return typ([True] * typ.LENGTH)
        return typ([rng.choice((True, False)) for _ in range(typ.LENGTH)])
    if issubclass(typ, Bitlist):
        n = _collection_length(rng, mode, typ.LIMIT, max_list_length)
        if mode == RandomizationMode.mode_max:
            return typ([True] * n)
        return typ([rng.choice((True, False)) for _ in range(n)])
    if issubclass(typ, (uint, boolean)):
        return _basic(rng, typ, mode)
    if issubclass(typ, Vector):
        return typ([
            get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length,
                                  max_list_length, mode, chaos)
            for _ in range(typ.LENGTH)
        ])
    if issubclass(typ, List):
        n = _collection_length(rng, mode, typ.LIMIT, max_list_length)
        return typ([
            get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length,
                                  max_list_length, mode, chaos)
            for _ in range(n)
        ])
    if issubclass(typ, Container):
        return typ(**{
            name: get_random_ssz_object(rng, field_typ, max_bytes_length,
                                        max_list_length, mode, chaos)
            for name, field_typ in typ.fields().items()
        })
    if issubclass(typ, Union):
        selector = rng.randrange(len(typ.OPTIONS)) if mode.is_changing() else 0
        inner_typ = typ.OPTIONS[selector]
        if inner_typ is None:
            return typ(selector=selector)
        return typ(selector=selector, value=get_random_ssz_object(
            rng, inner_typ, max_bytes_length, max_list_length, mode, chaos
        ))
    raise TypeError(f"cannot randomize {typ}")
