"""Plain data -> SSZ View decoder (inverse of debug/encode.py).

Fills the role of reference eth2spec/debug/decode.py:9-42 (own
implementation): rebuilds a typed View from encoder output, re-checking any
embedded hash_tree_root annotations along the way.
"""
from ..utils.ssz.ssz_typing import (
    Bitlist, Bitvector, ByteList, ByteVector, Container, List, Union, Vector,
    boolean, uint,
)


def _bits_from_hex(typ, hexstr, length=None):
    data = bytes.fromhex(hexstr[2:])
    return typ.decode_bytes(data)


def decode(data, typ):
    if issubclass(typ, (uint, boolean)):
        return typ(int(data))
    if issubclass(typ, (ByteVector, ByteList)):
        return typ(bytes.fromhex(data[2:]))
    if issubclass(typ, (Bitvector, Bitlist)):
        return _bits_from_hex(typ, data)
    if issubclass(typ, (Vector, List)):
        return typ([decode(elem, typ.ELEM_TYPE) for elem in data])
    if issubclass(typ, Container):
        values = {}
        for name, field_typ in typ.fields().items():
            values[name] = decode(data[name], field_typ)
            if name + "_hash_tree_root" in data:
                expected = data[name + "_hash_tree_root"].lower()
                got = "0x" + values[name].hash_tree_root().hex()
                assert got == expected, f"{name}: root mismatch {got} != {expected}"
        out = typ(**values)
        if "hash_tree_root" in data:
            expected = data["hash_tree_root"].lower()
            got = "0x" + out.hash_tree_root().hex()
            assert got == expected, f"container root mismatch {got} != {expected}"
        return out
    if issubclass(typ, Union):
        selector = int(data["selector"])
        inner_typ = typ.OPTIONS[selector]
        inner = None if inner_typ is None else decode(data["value"], inner_typ)
        return typ(selector=selector, value=inner)
    raise TypeError(f"cannot decode into {typ}")
