"""consensus_specs_tpu — a TPU-native executable Ethereum PoS consensus spec.

A ground-up rebuild of the capabilities of the eth2 `consensus-specs` pyspec
(reference: /root/reference, v1.1.3): executable phase0/altair/merge specs with
mainnet+minimal presets, an SSZ engine, a multi-backend BLS switchboard whose
fast path is XLA-compiled BLS12-381 batch verification for TPU (ops/), a test harness, and
cross-client test-vector generators.

Layout (mirrors SURVEY.md layer map):
  utils/      L0: SSZ typing+merkleization, hashing, BLS switchboard, merkle helpers
  config/     L1: preset/config YAML loaders
  specsrc/    L2: fork spec sources (authored Python, layered like the reference's
              markdown: later forks override earlier definitions)
  builder.py  L3: spec builder — binds (fork, preset, config) -> importable module
  ops/        TPU compute plane: limb field arithmetic, curve ops, pairing kernels
  parallel/   device-mesh sharding of the committee/epoch axes
  gen/        L6: test-vector generator runtime
  debug/      SSZ<->JSON codecs + random object generation
"""

__version__ = "0.1.0"
