"""Batched signature verification plane: collect-then-verify for epoch replay.

The reference verifies signatures one at a time inside the state-transition
call stack (process_operations loop, reference
specs/phase0/beacon-chain.md:1742-1756; fork-choice on_attestation,
fork-choice.md:393-410). On TPU the win comes from batching every independent
check of a span of blocks into a few device pipelines (SURVEY.md §2.7/P1 —
the committee axis is the DP axis). This module provides that seam:

  with SignatureCollector(spec) as col:
      for block in blocks:
          spec.state_transition(state, block)   # signature checks RECORDED
  ok = col.flush()                              # ... and verified batched
  assert ok.all()

What is deferred vs eager — chosen by the spec's own failure semantics:

- DEFERRED (assert-style; a failure invalidates the whole span anyway):
  aggregate attestation checks (``bls.FastAggregateVerify`` /
  ``bls.AggregateVerify``, incl. attester slashings and altair's
  ``eth_fast_aggregate_verify``), the block proposer signature
  (``verify_block_signature``), and the assert-style ``bls.Verify`` calls
  of ``process_randao``, ``process_voluntary_exit`` and
  ``process_proposer_slashing`` (handler-scoped interception) — every
  independent mainline-fork check rides the batched plane. The custody
  draft's assert-style reveals stay eager (small, draft-only).
- EAGER (oracle, unchanged): ``bls.Verify`` everywhere else — because
  ``process_deposit`` uses it CONDITIONALLY (an invalid deposit PoP skips
  the validator instead of failing the block, reference
  specs/phase0/beacon-chain.md:1871-1887); deferring it optimistically
  would change the post-state.

``flush()`` runs the recorded checks through the TPU backend's batched entry
points, grouped by committee-size bucket so a lone 512-wide sync aggregate
does not pad the whole attestation batch. Bit-identical to the per-call
oracle (cross-checked in tests/test_batch_verify.py). If any check fails,
the span is invalid — the caller re-runs with per-call verification to
locate the offending block (the reference's always-sequential slow path).
"""
from typing import List, Sequence, Tuple

import numpy as np

# hoisted to module scope (was re-imported inside the per-check loop of
# _bucket_of on every flush)
from .ops.bls_backend import _k_bucket
from .utils import bls


class CollectedCheck:
    __slots__ = ("kind", "pubkeys", "messages", "signature")

    def __init__(self, kind: str, pubkeys, messages, signature):
        self.kind = kind  # "fast_aggregate" | "aggregate"
        self.pubkeys = pubkeys
        self.messages = messages  # one message (fast_aggregate) or per-key list
        self.signature = signature


class SignatureCollector:
    """Context manager recording the spec's assert-style BLS verifications,
    answering True during collection; ``flush()`` verifies them batched."""

    def __init__(self, spec=None):
        self.spec = spec
        self.checks: List[CollectedCheck] = []
        # captured eagerly so flush_oracle() resolves through the REAL
        # functions even while the context is active (looking bls.X up at
        # call time inside the context would hit the interceptor and loop)
        self._orig_fast_aggregate_verify = bls.FastAggregateVerify
        self._orig_aggregate_verify = bls.AggregateVerify
        self._orig_verify = bls.Verify
        self._saved_bls: Tuple = ()
        self._saved_vbs = None
        self._saved_handlers: List = []
        # True only while inside process_randao / process_voluntary_exit:
        # their bls.Verify calls are assert-style and safe to defer, unlike
        # process_deposit's conditional use
        self._defer_verify = False

    # -- switchboard interception ------------------------------------------

    def _fast_aggregate_verify(self, pubkeys, message, signature):
        if not bls.bls_active:
            # stub mode (--disable-bls test runs): blocks carry stub
            # signatures that must NOT reach real crypto at flush time;
            # mirror only_with_bls's stub answer and record nothing
            return True
        if len(pubkeys) == 0:
            # the reference returns False without any crypto; preserve that
            # exactly rather than deferring (reference utils/bls.py:67-74)
            return False
        self.checks.append(
            CollectedCheck(
                "fast_aggregate",
                [bytes(pk) for pk in pubkeys],
                bytes(message),
                bytes(signature),
            )
        )
        return True

    def _aggregate_verify(self, pubkeys, messages, signature):
        if not bls.bls_active:
            return True
        if len(pubkeys) == 0 or len(pubkeys) != len(messages):
            return False
        self.checks.append(
            CollectedCheck(
                "aggregate",
                [bytes(pk) for pk in pubkeys],
                [bytes(m) for m in messages],
                bytes(signature),
            )
        )
        return True

    def _verify_block_signature(self, state, signed_block):
        if not bls.bls_active:
            return True
        spec = self.spec
        proposer = state.validators[signed_block.message.proposer_index]
        signing_root = spec.compute_signing_root(
            signed_block.message,
            spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER),
        )
        self.checks.append(
            CollectedCheck(
                "fast_aggregate",
                [bytes(proposer.pubkey)],
                bytes(signing_root),
                bytes(signed_block.signature),
            )
        )
        return True

    def _verify(self, pubkey, message, signature):
        """bls.Verify interceptor: deferred only inside the assert-style
        handlers (randao/exit); everywhere else — deposits included — the
        real oracle answers eagerly."""
        if not self._defer_verify:
            return self._orig_verify(pubkey, message, signature)
        if not bls.bls_active:
            return True
        self.checks.append(
            CollectedCheck(
                "fast_aggregate", [bytes(pubkey)], bytes(message), bytes(signature)
            )
        )
        return True

    def _deferring(self, handler):
        """Wrap a spec handler so bls.Verify defers for its duration."""
        def wrapped(*args, **kwargs):
            was = self._defer_verify
            self._defer_verify = True
            try:
                return handler(*args, **kwargs)
            finally:
                self._defer_verify = was

        return wrapped

    def __enter__(self):
        self._orig_verify = bls.Verify  # refresh: another collector may wrap
        self._saved_bls = (
            bls.FastAggregateVerify, bls.AggregateVerify, self._orig_verify,
        )
        bls.FastAggregateVerify = self._fast_aggregate_verify
        bls.AggregateVerify = self._aggregate_verify
        bls.Verify = self._verify
        if self.spec is not None and hasattr(self.spec, "verify_block_signature"):
            self._saved_vbs = self.spec.verify_block_signature
            self.spec.verify_block_signature = self._verify_block_signature
        if self.spec is not None:
            for name in ("process_randao", "process_voluntary_exit",
                         "process_proposer_slashing"):
                handler = getattr(self.spec, name, None)
                if handler is not None:
                    self._saved_handlers.append((name, handler))
                    setattr(self.spec, name, self._deferring(handler))
        return self

    def __exit__(self, *exc):
        bls.FastAggregateVerify, bls.AggregateVerify, bls.Verify = self._saved_bls
        if self._saved_vbs is not None:
            self.spec.verify_block_signature = self._saved_vbs
            self._saved_vbs = None
        for name, handler in self._saved_handlers:
            setattr(self.spec, name, handler)
        self._saved_handlers = []
        return False

    # -- batched resolution -------------------------------------------------

    def _unique_checks(self) -> Tuple[List[int], List[List[int]]]:
        """Dedup identical recorded checks: the same attestation included
        in multiple blocks is one verification, fanned out to every
        occurrence. Returns (first-occurrence indices in record order,
        per-unique member index lists)."""
        order: List[int] = []
        members: List[List[int]] = []
        seen = {}
        for i, c in enumerate(self.checks):
            key = _dedup_key(c)
            u = seen.get(key)
            if u is None:
                seen[key] = len(order)
                order.append(i)
                members.append([i])
            else:
                members[u].append(i)
        return order, members

    def flush(self, backend=None, mesh=None, service=None,
              rlc: bool = False) -> np.ndarray:
        """Verify all recorded checks; returns a bool array in record order.

        Identical checks (same kind/pubkeys/message(s)/signature) are
        verified ONCE and the result fanned out to every occurrence.

        With ``service`` (a serve.VerificationService), the unique checks
        ride the streaming plane — micro-batched with whatever else the
        service is carrying, cached, deduped against other submitters.
        Otherwise checks are grouped by (kind, K-bucket) so each device
        batch pads to its own committee-size bucket (ops/bls_backend.py
        _K_BUCKETS). With ``mesh``, each bucket's batch axis is sharded
        over the mesh (SURVEY §2.7/P1 — the committee axis is the DP
        axis).

        ``rlc=True`` resolves the whole span through the backend's
        random-linear-combination path (``batch_verify_rlc``): ONE final
        exponentiation for all recorded checks instead of one per check,
        with bisection recovering exact per-item verdicts on failure —
        the epoch-replay bench opts in via CONSENSUS_SPECS_TPU_RLC. Kept
        opt-in here (unlike the serve plane's default-on) so correctness
        cross-checks against flush_oracle() keep exercising the per-item
        device path."""
        out = np.zeros(len(self.checks), dtype=bool)
        order, members = self._unique_checks()

        if service is not None:
            if backend is not None or mesh is not None:
                raise ValueError(
                    "flush(service=...) uses the service's own backend and "
                    "sharding; pass backend/mesh to the VerificationService "
                    "instead"
                )
            if rlc:
                raise ValueError(
                    "flush(service=..., rlc=True): the service routes its "
                    "micro-batches through the RLC path itself "
                    "(CONSENSUS_SPECS_TPU_RLC governs it)"
                )
            futures = [
                service.submit(c.kind, c.pubkeys, c.messages, c.signature)
                for c in (self.checks[i] for i in order)
            ]
            for m, fut in zip(members, futures):
                out[m] = bool(fut.result())
            return out

        if backend is None:
            from .ops import bls_backend as backend  # noqa: F811

        if rlc:
            checks = [self.checks[i] for i in order]
            res = backend.batch_verify_rlc(
                [(c.kind, c.pubkeys, c.messages, c.signature)
                 for c in checks],
                mesh=mesh,
            )
            for u, r in enumerate(res):
                out[members[u]] = bool(r)
            return out

        groups = {}
        for u, i in enumerate(order):
            c = self.checks[i]
            key = (c.kind, _bucket_of(len(c.pubkeys)))
            groups.setdefault(key, []).append(u)

        for (kind, _bucket), uidxs in groups.items():
            checks = [self.checks[order[u]] for u in uidxs]
            if kind == "fast_aggregate":
                res = backend.batch_fast_aggregate_verify(
                    [c.pubkeys for c in checks],
                    [c.messages for c in checks],
                    [c.signature for c in checks],
                    mesh=mesh,
                )
            else:
                res = backend.batch_aggregate_verify(
                    [c.pubkeys for c in checks],
                    [c.messages for c in checks],
                    [c.signature for c in checks],
                    mesh=mesh,
                )
            for r, u in zip(res, uidxs):
                out[members[u]] = bool(r)
        return out

    def flush_oracle(self) -> np.ndarray:
        """Sequential pure-Python resolution of the same checks (the
        reference's execution model) — the cross-check for flush()."""
        out = np.zeros(len(self.checks), dtype=bool)
        for i, c in enumerate(self.checks):
            if c.kind == "fast_aggregate":
                out[i] = self._orig_fast_aggregate_verify(c.pubkeys, c.messages, c.signature)
            else:
                out[i] = self._orig_aggregate_verify(c.pubkeys, c.messages, c.signature)
        return out


def _bucket_of(k: int) -> int:
    return _k_bucket(max(1, k))


def _dedup_key(c: CollectedCheck):
    msgs = c.messages if isinstance(c.messages, bytes) else tuple(c.messages)
    return (c.kind, tuple(c.pubkeys), msgs, c.signature)


def replay_blocks_batched(spec, state, signed_blocks: Sequence) -> np.ndarray:
    """Replay ``signed_blocks`` through ``spec.state_transition`` with all
    assert-style signature checks collected, then batch-verified. Mutates
    ``state``. Returns the per-check result array (all True = valid span)."""
    with SignatureCollector(spec) as col:
        for signed_block in signed_blocks:
            spec.state_transition(state, signed_block)
    return col.flush()


def feed_attestations_batched(spec, store, attestations: Sequence) -> np.ndarray:
    """Feed wire attestations to fork-choice ``on_attestation`` with their
    FastAggregateVerify checks collected, then batch-verified — the
    fork-choice side of the hot loop (reference
    specs/phase0/fork-choice.md:393-410). Store mutations happen
    optimistically during collection; a False in the result means the span
    must be re-fed per-call against a fresh store (the reference's
    always-sequential path)."""
    with SignatureCollector(spec) as col:
        for attestation in attestations:
            spec.on_attestation(store, attestation)
    return col.flush()


def feed_attestations_streamed(spec, store, attestations, service=None
                               ) -> np.ndarray:
    """Streaming twin of ``feed_attestations_batched``: attestations come
    from an ITERATOR (a live gossip feed), and each recorded check is
    submitted to the serve plane the moment it is recorded — verification
    overlaps ingestion instead of waiting for the span to end, and
    duplicates across the stream (the same aggregate from many peers) are
    verified once by the service's cache/dedup layer.

    With ``service=None`` a private VerificationService is created for
    the call (constructed BEFORE the collector context so its fallback
    oracle captures the real bls functions) and drained afterwards.
    Returns the per-check bool array in record order, exactly like the
    batched feeder."""
    owned = service is None
    if owned:
        from .serve import VerificationService

        service = VerificationService()
    futures = []
    try:
        with SignatureCollector(spec) as col:
            n_seen = 0
            for attestation in attestations:
                spec.on_attestation(store, attestation)
                for c in col.checks[n_seen:]:
                    futures.append(
                        service.submit(c.kind, c.pubkeys, c.messages,
                                       c.signature)
                    )
                n_seen = len(col.checks)
        return np.array([bool(f.result()) for f in futures], dtype=bool)
    finally:
        if owned:
            service.close()
