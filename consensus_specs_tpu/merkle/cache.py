"""Incremental Merkle layer cache with batched dirty-set updates.

``LevelTree`` is the storage behind every SSZ series cache
(``ssz_typing._ChunkTree`` is an alias): the PRESENT nodes of each level
of a virtual zero-padded tree of fixed depth, built level-batched
through ``merkle/levels.py`` (one native ``sha256_hash_many`` call per
level) and updated through ``update()`` — a whole dirty CHUNK SET plus
appends propagate level by level, re-hashing only the touched parent
frontier, and each level's touched pairs go through one batched hash
call instead of a hashlib round trip per dirty path node. A block's
state delta therefore costs O(log N · changed) node recomputes
(``merkle.dirty_nodes``) across at most ``depth`` hash calls.

Layout contract (shared with ``utils/ssz/proofs.py`` which reads
``layers`` directly, and ``utils/merkle_minimal.py``): ``layers[d]`` is
the list of present nodes at height ``d`` above the chunks; absent right
siblings are the zero-subtree hashes of their height; ``root()`` folds
the top present node with zero hashes up to ``depth``. Bit-identical to
``merkleize_chunks`` (cross-checked in tests/test_ssz_incremental.py and
the merkle smoke).
"""
from typing import Dict, Optional, Sequence

from . import levels as _levels
from .levels import ZERO_HASHES


class LevelTree:
    """Merkle layer cache over a virtual zero-padded tree of fixed depth.

    Stores only the present nodes of each layer, so a List[_, 2^40] with
    n chunks costs ~2n nodes. `set_chunk`/`append` update one chunk;
    `update` applies a whole dirty set + appends with per-level batched
    hashing; `root()` folds the top present node with zero hashes up to
    the type's depth."""

    __slots__ = ("depth", "layers")

    def __init__(self, depth: int, chunks: Sequence[bytes]):
        self.depth = depth
        self.layers = [list(chunks)]
        self._build_above(0)

    def _build_above(self, level: int) -> None:
        del self.layers[level + 1 :]
        cur = self.layers[level]
        lv = level
        while len(cur) > 1:
            cur = _levels.hash_level(cur, lv)
            self.layers.append(cur)
            lv += 1

    def n_chunks(self) -> int:
        return len(self.layers[0])

    def set_chunk(self, i: int, chunk: bytes) -> None:
        self.update({i: chunk})

    def append(self, chunk: bytes) -> None:
        self.update(None, (chunk,))

    def update(
        self,
        updates: Optional[Dict[int, bytes]] = None,
        appends: Optional[Sequence[bytes]] = None,
    ) -> None:
        """Write ``updates`` (chunk index -> new chunk) and ``appends``
        (new chunks past the current width), then re-hash the touched
        parent frontier level by level — each level one batched call."""
        base = self.layers[0]
        dirty = set()
        if updates:
            for i, c in updates.items():
                base[i] = c
                dirty.add(i >> 1)
        if appends:
            start = len(base)
            base.extend(appends)
            # parents of the appended range, plus the boundary pair the
            # last old chunk now shares with the first appended one
            dirty.update(range(start >> 1, (len(base) + 1) >> 1))
        if not dirty:
            return
        for lv in range(len(self.layers) - 1):
            cur = self.layers[lv]
            up = self.layers[lv + 1]
            parents = sorted(dirty)
            blob = bytearray()
            zh = ZERO_HASHES[lv]
            for pi in parents:
                blob += cur[2 * pi]
                blob += cur[2 * pi + 1] if 2 * pi + 1 < len(cur) else zh
            digests = _levels.hash_pair_blob(bytes(blob))
            _levels.counters["dirty_nodes"] += len(parents)
            dirty = set()
            for k, pi in enumerate(parents):
                h = digests[k << 5 : (k + 1) << 5]
                if pi == len(up):
                    up.append(h)
                else:
                    up[pi] = h
                dirty.add(pi >> 1)
        # growth past a power-of-two boundary needs new top layers
        while len(self.layers[-1]) > 1:
            self._build_above(len(self.layers) - 1)

    def root(self) -> bytes:
        if not self.layers[0]:
            return ZERO_HASHES[self.depth]
        node = self.layers[-1][0]
        for lv in range(len(self.layers) - 1, self.depth):
            node = _levels.hash_level([node, ZERO_HASHES[lv]], lv)[0]
        return node
