"""Level-batched SSZ hashing core + the Merkleization mode knob.

This module is the bottom of the merkle plane (ISSUE 18): every caller
that hashes a tree LEVEL — ``merkleize_chunks``, the incremental layer
cache (``merkle/cache.py``), the cross-element cold-build plane
(``merkle/plane.py``), and the deposit-tree level builder
(``utils/merkle_minimal.py``) — routes the whole level through ONE
native ``sha256_hash_many`` call (csrc/sha256_batch.c) instead of a
hashlib round trip per node pair.

The mode knob (``CONSENSUS_SPECS_TPU_MERKLE``):

- ``auto``   (default) — native batching wherever the shared library is
  available, byte-identical to the python path by construction.
- ``native`` — demand the native path; a missing library still falls
  back to hashlib per call but counts ``merkle.fallbacks`` so the bench
  gate can see the degradation.
- ``python`` — the pure-hashlib differential oracle: no native calls, no
  cross-element plane. ``CONSENSUS_SPECS_TPU_MERKLE_DIFF=1`` makes the
  SSZ facade (``utils/ssz/ssz_impl.hash_tree_root``) re-derive every
  root through this path on a fresh decode and assert bit-identity.

Import cost is stdlib + the lazy native loader only: ``ssz_typing``
imports this module at its own import time, so nothing here may import
the SSZ engine, jax, or the obs plane eagerly (profiling/latency are
reached lazily from ``export_gauges``/``note_root_seconds``).
"""
import contextlib
import hashlib
import os
from typing import Dict, List, Optional, Sequence

MODE_ENV = "CONSENSUS_SPECS_TPU_MERKLE"
DIFF_ENV = "CONSENSUS_SPECS_TPU_MERKLE_DIFF"

# below this many pairs the ctypes call gate + buffer join costs more
# than hashlib; same threshold the pre-plane merkleize_chunks used
MIN_NATIVE_PAIRS = 8

# zero-subtree table: ZERO_HASHES[k] is the root of 2^k zero chunks.
# Recomputed locally (sha256 is deterministic) so this module never
# imports ssz_typing — ssz_typing imports US.
ZERO_HASHES: List[bytes] = [b"\x00" * 32]
for _ in range(64):
    ZERO_HASHES.append(hashlib.sha256(ZERO_HASHES[-1] * 2).digest())

# plane counters, exported as the merkle.* gauge family (obs/registry.py)
counters: Dict[str, int] = {
    "native_levels": 0,   # levels hashed through one native call
    "cache_hits": 0,      # series re-roots served from a warm layer tree
    "dirty_nodes": 0,     # nodes recomputed by batched dirty-set updates
    "fallbacks": 0,       # native demanded/planned but python path used
}


def reset_counters() -> None:
    for k in counters:
        counters[k] = 0


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------

_forced: List[str] = []         # forced_mode() stack (benches, diff oracle)
_configured: Optional[str] = None  # configure() override (None = read env)
_native_fn = None               # resolved native hash_pairs, or False
_VALID_MODES = ("native", "python", "auto")


def _native():
    """The native pair hasher, resolved once; ``None`` if unavailable."""
    global _native_fn
    if _native_fn is None:
        try:
            from ..utils.native_sha256 import available, hash_pairs

            _native_fn = hash_pairs if available() else False
        except Exception:
            _native_fn = False
    return _native_fn or None


def configure(mode: Optional[str] = None) -> None:
    """Pin the mode programmatically; ``configure(None)`` re-reads the env
    on the next call (tests and benches flip modes without env games)."""
    global _configured
    if mode is not None and mode not in _VALID_MODES:
        raise ValueError(f"{MODE_ENV} mode {mode!r} not in {_VALID_MODES}")
    _configured = mode


@contextlib.contextmanager
def forced_mode(mode: str):
    """Scoped mode override — the differential oracle and the bench's
    python-baseline passes run under ``forced_mode("python")``."""
    if mode not in _VALID_MODES:
        raise ValueError(f"{MODE_ENV} mode {mode!r} not in {_VALID_MODES}")
    _forced.append(mode)
    try:
        yield
    finally:
        _forced.pop()


def requested_mode() -> str:
    """The knob as set (native|python|auto), before availability."""
    if _forced:
        return _forced[-1]
    if _configured is not None:
        return _configured
    m = os.environ.get(MODE_ENV, "auto").strip().lower() or "auto"
    return m if m in _VALID_MODES else "auto"


def mode() -> str:
    """The RESOLVED mode: what the hash path will actually do."""
    m = requested_mode()
    if m == "auto":
        return "native" if _native() is not None else "python"
    return m


def use_native() -> bool:
    """True when level calls should try the native path. In ``native``
    mode with the library missing this stays True so the per-call
    fallback is visible in ``merkle.fallbacks``."""
    return requested_mode() != "python" and (
        requested_mode() == "native" or _native() is not None
    )


def plane_enabled() -> bool:
    """Whether the cross-element cold-build plane may run: never in
    python mode (the oracle must be the plain per-element walk), and
    only when the native library is really present (batching through
    hashlib would just move the python loop around)."""
    return requested_mode() != "python" and _native() is not None


def diff_enabled() -> bool:
    return os.environ.get(DIFF_ENV) == "1"


# ---------------------------------------------------------------------------
# the level hashers
# ---------------------------------------------------------------------------


def hash_pair_blob(blob: bytes) -> bytes:
    """Hash a contiguous buffer of 64-byte messages into the concatenated
    32-byte digests — the primitive every batched level reduces through."""
    n_pairs = len(blob) >> 6
    if n_pairs >= MIN_NATIVE_PAIRS and use_native():
        fn = _native()
        if fn is not None:
            counters["native_levels"] += 1
            return fn(blob)
        counters["fallbacks"] += 1
    sha = hashlib.sha256
    return b"".join(
        sha(blob[i << 6 : (i + 1) << 6]).digest() for i in range(n_pairs)
    )


def hash_level(level: Sequence[bytes], depth: int) -> List[bytes]:
    """Hash one tree level into its parents; an odd tail pairs with the
    zero-subtree hash of ``depth`` (the canonical sparse-padding rule)."""
    n = len(level)
    if n % 2:
        level = list(level)
        level.append(ZERO_HASHES[depth])
        n += 1
    n_pairs = n >> 1
    if n_pairs >= MIN_NATIVE_PAIRS and use_native():
        fn = _native()
        if fn is not None:
            counters["native_levels"] += 1
            digests = fn(b"".join(level))
            return [digests[i << 5 : (i + 1) << 5] for i in range(n_pairs)]
        counters["fallbacks"] += 1
    sha = hashlib.sha256
    return [
        sha(level[2 * i] + level[2 * i + 1]).digest() for i in range(n_pairs)
    ]


def build_levels(chunks: Sequence[bytes]) -> List[List[bytes]]:
    """All levels from ``chunks`` up to a single present node (the stored
    half of a virtual zero-padded tree; see ``merkle/cache.py``)."""
    levels = [list(chunks)]
    lv = 0
    while len(levels[-1]) > 1:
        levels.append(hash_level(levels[-1], lv))
        lv += 1
    return levels


# ---------------------------------------------------------------------------
# obs surface (lazy: nothing above imports the obs/ops planes)
# ---------------------------------------------------------------------------


def export_gauges() -> None:
    """Publish the counters as the ``merkle.*`` gauge family."""
    from ..ops import profiling

    profiling.set_gauge("merkle.native_levels", float(counters["native_levels"]))
    profiling.set_gauge("merkle.cache_hits", float(counters["cache_hits"]))
    profiling.set_gauge("merkle.dirty_nodes", float(counters["dirty_nodes"]))
    profiling.set_gauge("merkle.fallbacks", float(counters["fallbacks"]))


def note_root_seconds(seconds: float) -> None:
    """One facade-level ``hash_tree_root`` observation into the
    ``latency[merkle_root]`` stage histogram; never raises (the facade
    must stay usable before/without the obs plane)."""
    try:
        from ..obs import latency

        latency.note_stage("merkle_root", seconds)
    except Exception:
        pass
