"""Merkleization-plane canary (`make merkle-smoke`, CI).

Bit-identity of the native batched path against the pure-python oracle
over every SSZ shape class the engine Merkleizes — basic vectors and
lists (length mix-ins included), bitfields, byte vectors/lists, nested
containers, composite series through the cross-element plane, dynamic
shapes that must FALL BACK, and far-from-full capacities whose roots are
mostly zero-subtree padding — plus a seeded random incremental-cache
invalidation sweep: random dirty sets, appends, and deep aliased
mutations re-rooted through the warm layer cache and demanded identical
to a from-scratch cold rebuild every round.

Every check appends a journal record; on failure the journal dumps to
``merkle_flight.jsonl`` (uploaded as a CI artifact). Crypto-free and
compile-free: no pairings, no spec build, no XLA — safe to run anywhere,
fast enough for every CI push. Exit 0 on pass, 1 with a diagnosis.
"""
import json
import os
import random
import sys

JOURNAL_PATH = "merkle_flight.jsonl"
SEED = 20240818


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from . import levels as _levels
    from ..utils.ssz.ssz_typing import (
        Bitlist, Bitvector, ByteList, Bytes32, Bytes48, Container,
        List as SSZList, Vector, boolean, uint8, uint16, uint64, uint256,
    )

    journal = []
    failures = []

    class Checkpoint(Container):
        epoch: uint64
        root: Bytes32

    class Leaf(Container):
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        effective_balance: uint64
        slashed: boolean
        activation_eligibility_epoch: uint64
        activation_epoch: uint64
        exit_epoch: uint64
        withdrawable_epoch: uint64

    class Nested(Container):
        tag: uint16
        flags: Bitvector[21]
        checkpoint: Checkpoint
        words: Vector[uint64, 5]
        roots: Vector[Bytes32, 3]

    rng = random.Random(SEED)

    def rbytes(n):
        return bytes(rng.randrange(256) for _ in range(n))

    def leaf(i):
        return Leaf(
            pubkey=Bytes48(rbytes(48)),
            withdrawal_credentials=Bytes32(rbytes(32)),
            effective_balance=uint64(rng.randrange(2**40)),
            slashed=boolean(rng.randrange(2)),
            activation_eligibility_epoch=uint64(rng.randrange(2**20)),
            activation_epoch=uint64(rng.randrange(2**20)),
            exit_epoch=uint64(rng.randrange(2**20)),
            withdrawable_epoch=uint64(rng.randrange(2**20)),
        )

    def nested(i):
        return Nested(
            tag=uint16(i % 2**16),
            flags=Bitvector[21](*[bool(rng.randrange(2))
                                  for _ in range(21)]),
            checkpoint=Checkpoint(epoch=uint64(i), root=Bytes32(rbytes(32))),
            words=Vector[uint64, 5](*[uint64(rng.randrange(2**50))
                                      for _ in range(5)]),
            roots=Vector[Bytes32, 3](*[Bytes32(rbytes(32))
                                       for _ in range(3)]),
        )

    def check(name, view) -> bytes:
        """native root == python-oracle root on a fresh decode; returns
        the agreed root for reuse."""
        typ = type(view)
        with _levels.forced_mode("native"):
            nat = bytes(typ.decode_bytes(view.encode_bytes())
                        .hash_tree_root())
        with _levels.forced_mode("python"):
            ora = bytes(typ.decode_bytes(view.encode_bytes())
                        .hash_tree_root())
        ok = nat == ora
        journal.append({"check": name, "ok": ok,
                        "native": nat.hex(), "python": ora.hex()})
        if not ok:
            failures.append(f"{name}: native {nat.hex()[:16]}.. != "
                            f"python {ora.hex()[:16]}..")
        return nat

    # -- shape-class sweep ------------------------------------------------
    check("vector/basic", Vector[uint64, 13](*[uint64(i * 3 + 1)
                                               for i in range(13)]))
    check("vector/uint8", Vector[uint8, 100](*[uint8(i % 251)
                                               for i in range(100)]))
    check("vector/uint256", Vector[uint256, 3](*[uint256(2**200 + i)
                                                 for i in range(3)]))
    check("vector/composite", Vector[Checkpoint, 9](
        *[Checkpoint(epoch=uint64(i), root=Bytes32(rbytes(32)))
          for i in range(9)]))
    for n in (0, 1, 7, 8, 33, 1000):  # list lengths incl. mix-in edges
        check(f"list/uint64/n={n}",
              SSZList[uint64, 2**18](*[uint64(rng.randrange(2**60))
                                       for _ in range(n)]))
    check("list/composite/plane", SSZList[Leaf, 2**40](
        *[leaf(i) for i in range(300)]))
    check("list/composite/small-fallback", SSZList[Leaf, 2**40](
        *[leaf(i) for i in range(3)]))
    check("list/nested-containers", SSZList[Nested, 2**16](
        *[nested(i) for i in range(40)]))
    # dynamically-shaped elements: the plane MUST fall back, roots must
    # still match
    inner = SSZList[uint64, 64]
    check("list/dynamic-elements-fallback", SSZList[inner, 128](
        *[inner(*[uint64(j) for j in range(i % 5)]) for i in range(20)]))
    for n in (0, 1, 5, 8, 255, 256, 257):
        check(f"bitlist/n={n}",
              Bitlist[2**12](*[bool(rng.randrange(2)) for _ in range(n)]))
    check("bitvector/513", Bitvector[513](*[bool(rng.randrange(2))
                                            for _ in range(513)]))
    check("bytelist", ByteList[2**14](rbytes(777)))
    check("bytes48", Bytes48(rbytes(48)))
    # zero-subtree padding: tiny occupancy of a 2^32 capacity
    check("list/zero-padding", SSZList[Bytes32, 2**32](
        *[Bytes32(rbytes(32)) for _ in range(5)]))
    check("container/nested", nested(7))
    check("container/defaults", Nested())

    # -- incremental invalidation sweep ------------------------------------
    regs = SSZList[Leaf, 2**40](*[leaf(i) for i in range(300)])
    bal = SSZList[uint64, 2**40](*[uint64(32 * 10**9) for _ in range(300)])
    bits = Bitlist[2**12](*[bool(rng.randrange(2)) for _ in range(100)])
    with _levels.forced_mode("native"):
        regs.hash_tree_root(), bal.hash_tree_root(), bits.hash_tree_root()
    for rnd in range(8):
        # random dirty set: replacements, deep aliased mutations, appends
        for i in rng.sample(range(len(regs)), 12):
            regs[i] = leaf(1000 + rnd * 100 + i)
        for i in rng.sample(range(len(regs)), 12):
            regs[i].effective_balance = uint64(rng.randrange(2**40))
        regs.append(leaf(2000 + rnd))
        for i in rng.sample(range(len(bal)), 25):
            bal[i] = uint64(rng.randrange(2**40))
        bal.append(uint64(rnd))
        for i in rng.sample(range(len(bits)), 10):
            bits[i] = not bits[i]
        bits.append(bool(rnd % 2))
        for name, view in (("registry", regs), ("balances", bal),
                           ("bitlist", bits)):
            with _levels.forced_mode("native"):
                warm = bytes(view.hash_tree_root())  # incremental path
            with _levels.forced_mode("python"):
                cold = bytes(type(view).decode_bytes(view.encode_bytes())
                             .hash_tree_root())
            ok = warm == cold
            journal.append({"check": f"incremental/{name}/round={rnd}",
                            "ok": ok, "native": warm.hex(),
                            "python": cold.hex()})
            if not ok:
                failures.append(
                    f"incremental/{name}/round={rnd}: warm cache root "
                    f"{warm.hex()[:16]}.. != from-scratch {cold.hex()[:16]}..")

    counters = dict(_levels.counters)
    journal.append({"check": "counters", "ok": True, **counters})

    if failures:
        print("merkle-smoke FAIL:")
        for f in failures:
            print(f"  {f}")
        with open(JOURNAL_PATH, "w") as fh:
            for rec in journal:
                fh.write(json.dumps(rec) + "\n")
        print(f"merkle-smoke: journal dumped to {JOURNAL_PATH}")
        return 1

    n_checks = sum(1 for r in journal if "native" in r)
    print(
        f"merkle-smoke OK: {n_checks} bit-identity checks (shape sweep + "
        f"8-round seeded invalidation sweep), native mode "
        f"{'available' if _levels.plane_enabled() else 'ABSENT (python)'}: "
        f"{counters['native_levels']} native levels, "
        f"{counters['cache_hits']} cache hits, "
        f"{counters['dirty_nodes']} dirty nodes, "
        f"{counters['fallbacks']} fallbacks"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
