"""Cross-element batched cold Merkleization (the ≥5x cold-root lever).

A cold ``List[Validator]``/``Vector[Bytes48]`` build is where the pure
python path burns its time: every element pays its own
``hash_tree_root`` — a python call tree plus ~2·chunks tiny hashlib
invocations, far below the ``MIN_NATIVE_PAIRS`` batching threshold, so
per-tree level batching never engages. This module turns the loop
sideways: it computes the roots of ALL elements of a series COLUMN-WISE
— one numpy interleave per field column, then one
``sha256_hash_many`` call per TREE LEVEL spanning every element at once
(8 native calls for a million Validators instead of ~9M hashlib calls).

Only statically-shaped element types batch: basics, ``ByteVector``,
``Bitvector``, ``Vector`` (packed or composite), and ``Container``s of
those. Anything with a length mix-in inside (List/Bitlist/ByteList) or
a Union returns ``None`` — the caller falls back to the per-element
walk and ``merkle.fallbacks`` counts it. Supported or not, roots are
bit-identical to the oracle by construction (zero-chunk padding at
level 0 reduces to exactly the sparse ZERO_HASHES rule), and the merkle
smoke + ``CONSENSUS_SPECS_TPU_MERKLE_DIFF=1`` assert it continuously.

This module imports the SSZ engine, so it must only ever be imported
LAZILY from ``ssz_typing`` (which imports ``merkle/levels`` at import
time — the reverse edge would cycle).
"""
from operator import attrgetter
from typing import List, Optional, Sequence

import numpy as np

from . import levels as _levels
from ..utils.ssz.ssz_typing import (
    Bitvector,
    ByteVector,
    Container,
    List as SSZList,
    Vector,
    _bits_to_bytes,
    is_basic_type,
    next_power_of_two,
)

# cross-element batching only pays past a handful of elements; below
# this the per-element walk is as fast and keeps its caches warmer
MIN_PLANE_ELEMS = 8

_PLAN_CACHE = {}


def _supported(typ) -> bool:
    """Statically-shaped element types whose column roots we can batch."""
    cached = _PLAN_CACHE.get(typ)
    if cached is not None:
        return cached
    if is_basic_type(typ):
        ok = True
    elif isinstance(typ, type) and issubclass(typ, (ByteVector, Bitvector)):
        ok = True
    elif isinstance(typ, type) and issubclass(typ, Container):
        ok = all(_supported(t) for t in typ._field_types.values())
    elif (isinstance(typ, type) and issubclass(typ, Vector)
          and not issubclass(typ, SSZList)):
        ok = _supported(typ.ELEM_TYPE)
    else:
        ok = False
    _PLAN_CACHE[typ] = ok
    return ok


def _reduce_rows(blob: bytes, width: int) -> bytes:
    """Merkleize N independent chunk rows of ``width`` (a power of two)
    laid out contiguously: each reduction level is one batched hash call
    across every row at once. Returns the N concatenated roots."""
    while width > 1:
        blob = _levels.hash_pair_blob(blob)
        width >>= 1
    return blob


def _pad_rows(raw: bytes, n: int, row_bytes: int, padded_bytes: int) -> bytes:
    """Lay N rows of ``row_bytes`` into zero-padded rows of
    ``padded_bytes`` (one numpy scatter, no per-row python)."""
    if row_bytes == padded_bytes:
        return raw
    rows = np.zeros((n, padded_bytes), dtype=np.uint8)
    rows[:, :row_bytes] = np.frombuffer(raw, dtype=np.uint8).reshape(
        n, row_bytes)
    return rows.tobytes()


_UINT_DTYPES = {1: np.uint8, 2: np.dtype("<u2"), 4: np.dtype("<u4"),
                8: np.dtype("<u8")}


def _basic_raw(typ, values: Sequence) -> bytes:
    """Little-endian packed encoding of a basic-typed column. Machine-word
    sizes go through one numpy ``fromiter`` instead of a per-value
    ``encode_bytes`` call — the dominant python cost of a cold column."""
    es = typ.type_byte_length()
    dt = _UINT_DTYPES.get(es)
    if dt is not None:
        # basic views are int subclasses — numpy consumes them directly
        return np.fromiter(values, dtype=dt, count=len(values)).tobytes()
    return b"".join(v.encode_bytes() for v in values)


def packed_basic_raw(typ, values: Sequence) -> Optional[bytes]:
    """Packed little-endian encoding of a basic series for the cold
    ``_chunks_root`` build, or ``None`` for non-machine-word widths
    (caller keeps its per-element join)."""
    if typ.type_byte_length() not in _UINT_DTYPES:
        return None
    return _basic_raw(typ, values)


def _column_roots(typ, values: Sequence) -> bytes:
    """Concatenated 32-byte hash_tree_roots of a COLUMN of same-typed
    values — the recursive core. ``typ`` must be ``_supported``."""
    n = len(values)
    if is_basic_type(typ):
        es = typ.type_byte_length()
        return _pad_rows(_basic_raw(typ, values), n, es, 32)
    if issubclass(typ, ByteVector):
        length = typ.LENGTH
        raw = b"".join(bytes(v) for v in values)
        if length <= 32:
            return _pad_rows(raw, n, length, 32)
        width = next_power_of_two((length + 31) // 32)
        return _reduce_rows(_pad_rows(raw, n, length, width * 32), width)
    if issubclass(typ, Bitvector):
        nbytes = (typ.LENGTH + 7) // 8
        raw = b"".join(_bits_to_bytes(v._bits) for v in values)
        width = next_power_of_two((nbytes + 31) // 32)
        return _reduce_rows(_pad_rows(raw, n, nbytes, width * 32), width)
    if issubclass(typ, Container):
        fields = list(typ._field_types.items())
        width = next_power_of_two(len(fields))
        rows = np.zeros((n, width, 32), dtype=np.uint8)
        for f, (name, ftyp) in enumerate(fields):
            # C-level column extraction (fields are plain instance
            # attributes; a python-loop getattr per cell dominates the
            # cold build otherwise)
            col = _column_roots(ftyp, list(map(attrgetter(name), values)))
            rows[:, f, :] = np.frombuffer(col, dtype=np.uint8).reshape(n, 32)
        return _reduce_rows(rows.tobytes(), width)
    if issubclass(typ, Vector):
        etyp = typ.ELEM_TYPE
        m = typ.LENGTH
        if is_basic_type(etyp):
            es = etyp.type_byte_length()
            raw = _basic_raw(etyp, [e for v in values for e in v._elems])
            width = next_power_of_two((m * es + 31) // 32)
            return _reduce_rows(_pad_rows(raw, n, m * es, width * 32), width)
        flat = [e for v in values for e in v._elems]
        sub = _column_roots(etyp, flat)
        width = next_power_of_two(m)
        return _reduce_rows(_pad_rows(sub, n, m * 32, width * 32), width)
    raise TypeError(f"unplanned column type {typ!r}")


def batched_element_roots(elems: Sequence) -> Optional[List[bytes]]:
    """Roots of every element of a composite series in one column-wise
    batched pass, or ``None`` when the plane is off / the element type
    carries dynamic shape (caller falls back to the per-element walk)."""
    n = len(elems)
    if n < MIN_PLANE_ELEMS or not _levels.plane_enabled():
        return None
    typ = type(elems[0])
    if not _supported(typ):
        _levels.counters["fallbacks"] += 1
        return None
    blob = _column_roots(typ, elems)
    return [blob[i << 5 : (i + 1) << 5] for i in range(n)]


def diff_check(obj, root: bytes) -> None:
    """The CONSENSUS_SPECS_TPU_MERKLE_DIFF=1 assert: re-derive ``root``
    through the pure-python oracle on a FRESH decode (cold caches, no
    native calls, no plane) and demand bit-identity."""
    with _levels.forced_mode("python"):
        fresh = type(obj).decode_bytes(obj.encode_bytes())
        oracle = bytes(fresh.hash_tree_root())
    if oracle != bytes(root):
        raise AssertionError(
            f"MERKLE DIVERGED: {type(obj).__name__} native root "
            f"{bytes(root).hex()} != python oracle {oracle.hex()}"
        )
