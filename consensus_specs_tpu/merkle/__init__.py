"""Device-adjacent Merkleization plane (ISSUE 18).

Layers, bottom up:

- ``levels``  — the batched level hasher + the
  ``CONSENSUS_SPECS_TPU_MERKLE=native|python|auto`` mode knob, the
  ``merkle.*`` counters, and the diff-gate switches. stdlib-only import.
- ``cache``   — ``LevelTree``: the incremental layer cache with batched
  dirty-set updates (aliased as ``ssz_typing._ChunkTree``).
- ``plane``   — cross-element column-batched cold roots for statically
  shaped series elements (imports the SSZ engine: LAZY import only from
  within ``ssz_typing``).
- ``smoke``   — ``make merkle-smoke``: bit-identity over every SSZ shape
  class + an incremental-cache invalidation sweep.

``plane`` and ``smoke`` are deliberately NOT imported here: ssz_typing
imports ``merkle.levels``/``merkle.cache`` at its own import time, and
pulling ``plane`` (which imports ssz_typing back) into the package
import would cycle.
"""
from . import cache, levels  # noqa: F401
from .cache import LevelTree  # noqa: F401
